"""The durable delta log: write-once fan-out for online model deltas.

Generalizes the event-log machinery (``online/events.py``) and the patch
journal (``online/delta.py``) into the replication substrate: the online
trainer's publisher appends each :class:`ModelDelta` ONCE, and any number
of serving replicas tail the file independently, each with its own atomic
cursor. One record per line, one ``os.write`` + ``os.fsync`` per record
on an O_APPEND fd, so a tailing replica never sees a torn line
mid-record and a crashed host never loses a record whose append
returned.

Record schema (``delta-log.jsonl``):

    {"seq": 12, "ts": 1754300000.1, "trace_id": "a1b2...",
     "delta": {"seq": 7, "event_horizon": 4096, "patches": {...}}}

    {"seq": 13, "ts": 1754300100.0, "trace_id": null,
     "snapshot": {"model_dir": "out/nightly/best", "note": "retrain"}}

``seq`` is the LOG sequence — dense, monotone, assigned by the writer
(resuming a log continues from the tail); ``delta.seq`` inside stays the
trainer's own delta sequence. A ``snapshot`` record is a full-model
marker: "a registry built from ``model_dir`` holds all state through this
log seq" — the catch-up shortcut for a replica whose lag exceeds its
threshold (docs/serving.md §"Replication": jump to the marker via
``prepare_standby``/``swap``, resume tailing at ``seq + 1``).

``trace_id`` is the publisher's trace id at append time: the tailer
applies under the same id, so the fleet merger joins publish→apply across
processes exactly like the HTTP header path does.

Reader discipline (:func:`iter_log`): the log is dense by construction, so
the reader PROVES exactly-once — a record whose seq it has already passed
is a duplicate (skipped, reported via ``on_duplicate``), a seq beyond the
next expected is a GAP (a corrupt or truncated log: refused loudly, never
silently skipped), and an unterminated final line is a write in flight
(waited on under ``follow``, skipped otherwise).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable, Iterator, Optional

from photon_tpu.online.delta import ModelDelta

logger = logging.getLogger("photon_tpu.replication")

LOG_FILENAME = "delta-log.jsonl"


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-created/renamed entry survives a
    crash (best-effort: not every platform/filesystem allows it)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class DeltaLogError(ValueError):
    """A corrupt delta log (torn non-tail line, seq gap, bad record) —
    must fail loud: a replica silently skipping records would serve
    permanently divergent coefficients."""


@dataclasses.dataclass(frozen=True)
class DeltaLogRecord:
    """One parsed log record: a delta or a full-snapshot marker."""

    seq: int
    ts: float
    trace_id: Optional[str]
    delta: Optional[ModelDelta] = None
    snapshot: Optional[dict] = None      # {"model_dir": ..., "note": ...}

    @property
    def is_snapshot(self) -> bool:
        return self.snapshot is not None

    @classmethod
    def from_dict(cls, d: dict, path: str = "<log>") -> "DeltaLogRecord":
        if not isinstance(d, dict) or "seq" not in d:
            raise DeltaLogError(f"{path}: record missing 'seq': {d!r:.120}")
        seq = int(d["seq"])
        ts = float(d.get("ts") or 0.0)
        tid = d.get("trace_id") or None
        if d.get("snapshot") is not None:
            snap = d["snapshot"]
            if not isinstance(snap, dict) or not snap.get("model_dir"):
                raise DeltaLogError(
                    f"{path}: seq {seq}: snapshot marker needs a model_dir")
            return cls(seq=seq, ts=ts, trace_id=tid, snapshot=dict(snap))
        try:
            delta = ModelDelta.from_wire(d.get("delta") or {})
        except ValueError as e:
            raise DeltaLogError(
                f"{path}: seq {seq}: bad delta record: {e}") from None
        return cls(seq=seq, ts=ts, trace_id=tid, delta=delta)


def _tail_next_seq(path: str, window: int = 1 << 16) -> int:
    """``last complete line's seq + 1`` from the file TAIL only (seqs are
    dense-monotone, so the last complete line suffices; a torn final line
    was never durably published and is ignored — same contract as
    ``events._tail_next_seq``)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb") as f:
        f.seek(max(0, size - window))
        tail = f.read()
    complete = tail[: tail.rfind(b"\n") + 1] if b"\n" in tail else b""
    lines = [x for x in complete.split(b"\n") if x.strip()]
    for raw in reversed(lines):
        try:
            return int(json.loads(raw).get("seq", -1)) + 1
        except (ValueError, AttributeError, TypeError):
            continue
    # No parseable line in the window (pathologically long records): full
    # scan, rare and loud-safe.
    next_seq = 0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                if not line.endswith("\n") or not line.strip():
                    continue
                try:
                    next_seq = max(next_seq,
                                   int(json.loads(line).get("seq", -1)) + 1)
                except ValueError:
                    continue
    except OSError:
        pass
    return next_seq


def log_next_seq(path: str) -> int:
    """The log HEAD: the seq the next append will get. ``head - cursor``
    is a replica's lag."""
    return _tail_next_seq(path)


def pending_records(path: str, start_seq: int = 0,
                    end_seq: Optional[int] = None) -> list:
    """Materialize ``[start_seq, end_seq)`` as a list (non-follow read).

    The control plane's canary window read: the controller snapshots a
    wave's records at soak begin so its promote decision appends exactly
    the records it adjudicated, even if the trainer keeps publishing into
    the side channel mid-soak. Same seq discipline as :func:`iter_log`
    (duplicates skipped, gaps refused)."""
    out = []
    for rec in iter_log(path, start_seq=start_seq, follow=False):
        if rec is None:
            continue
        if end_seq is not None and rec.seq >= end_seq:
            break
        out.append(rec)
    return out


class DeltaLogWriter:
    """Durable appender assigning dense monotone log ``seq``; resuming an
    existing log continues the sequence from its tail.

    Durability contract: ``append`` returns only after the record is
    written AND fsynced — the trainer's commit-after-publish step may
    advance past a delta the moment ``publish`` returns, so a host crash
    must not be able to eat a record the trainer already committed past.
    (The log's directory entry is fsynced once at creation; renames never
    touch this file afterwards, appends only.)"""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path) or "."
        os.makedirs(parent, exist_ok=True)
        existed = os.path.exists(path)
        self._next_seq = _tail_next_seq(path)
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        if not existed:
            _fsync_dir(parent)

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def _append_row(self, row: dict) -> int:
        seq = self._next_seq
        self._next_seq += 1
        row = {"seq": seq, "ts": time.time(), **row}
        os.write(self._fd, (json.dumps(row) + "\n").encode("utf-8"))
        # Page cache is not durability: a power loss could otherwise
        # drop a record whose publish already returned (class doc).
        os.fsync(self._fd)
        return seq

    def append(self, delta: ModelDelta,
               trace_id: Optional[str] = None) -> int:
        """Append one delta; returns its assigned log seq."""
        return self._append_row(
            {"trace_id": trace_id, "delta": delta.to_wire()})

    def append_snapshot(self, model_dir: str,
                        note: Optional[str] = None) -> int:
        """Append a full-snapshot marker: ``model_dir`` holds everything
        through the assigned seq. Written at log creation for the base
        model, and whenever a batch retrain republishes a full model —
        the catch-up shortcut lagging replicas jump to."""
        return self._append_row(
            {"trace_id": None,
             "snapshot": {"model_dir": str(model_dir),
                          **({"note": note} if note else {})}})

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "DeltaLogWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def iter_log(
    path: str,
    start_seq: int = 0,
    follow: bool = False,
    poll_s: float = 0.05,
    stop: Optional[Callable[[], bool]] = None,
    idle_yield_s: float = 0.0,
    on_duplicate: Optional[Callable[[int], None]] = None,
) -> Iterator[Optional[DeltaLogRecord]]:
    """Replay records with ``seq >= start_seq``; ``follow=True`` tails the
    log until ``stop()`` returns true.

    Seq discipline (the exactly-once half the cursor can't supply alone):
    the log is dense, so after the first yielded record each next record
    must carry exactly ``previous + 1``. A record at an already-passed seq
    is a DUPLICATE — skipped, counted via ``on_duplicate(seq)`` (a replayed
    or concatenated log must not double-apply). A record BEYOND the next
    expected seq is a GAP — :class:`DeltaLogError`, because silently
    skipping it would leave this replica permanently divergent.

    ``idle_yield_s > 0`` (follow mode) yields ``None`` after that long
    without a new record — an idle tick, so the tailer can refresh its lag
    gauge on a quiet stream. A final line without a newline is a write in
    flight: waited on under follow, skipped with a warning otherwise.
    """
    expected = int(start_seq)
    with open(path, "r", encoding="utf-8") as f:
        buf = ""
        idle_since = time.monotonic()
        while True:
            chunk = f.readline()
            if chunk:
                buf += chunk
                if not buf.endswith("\n"):
                    continue  # torn tail: wait for the rest of the line
                line, buf = buf.strip(), ""
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    raise DeltaLogError(
                        f"{path}: corrupt log line: {line[:120]!r}"
                    ) from None
                rec = DeltaLogRecord.from_dict(d, path)
                idle_since = time.monotonic()
                if rec.seq < expected:
                    if rec.seq >= start_seq:
                        # Passed already: a duplicate, never re-applied.
                        logger.warning(
                            "%s: duplicate log seq %d skipped (expected "
                            "%d)", path, rec.seq, expected)
                        if on_duplicate is not None:
                            on_duplicate(rec.seq)
                    continue  # below start_seq: already consumed, silent
                if rec.seq > expected:
                    raise DeltaLogError(
                        f"{path}: seq gap: expected {expected}, found "
                        f"{rec.seq} — the log is corrupt or truncated "
                        "mid-stream; refusing to skip records")
                expected = rec.seq + 1
                yield rec
                continue
            # EOF
            if not follow:
                if buf:
                    logger.warning(
                        "%s: unterminated final line (%d bytes) skipped — "
                        "a write in flight; the cursor has not passed it",
                        path, len(buf),
                    )
                return
            if stop is not None and stop():
                return
            if idle_yield_s > 0 and \
                    time.monotonic() - idle_since >= idle_yield_s:
                idle_since = time.monotonic()
                yield None  # idle tick
            time.sleep(poll_s)


def find_latest_snapshot(path: str,
                         min_seq: int = 0) -> Optional[DeltaLogRecord]:
    """The LATEST snapshot marker with ``seq >= min_seq`` (full scan —
    called once per catch-up decision, not per record). None when the log
    holds no eligible marker, in which case catch-up degrades to plain
    replay."""
    latest = None
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                if not line.endswith("\n") or not line.strip():
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue  # torn/corrupt lines are the reader's problem
                if d.get("snapshot") is not None and \
                        int(d.get("seq", -1)) >= min_seq:
                    latest = DeltaLogRecord.from_dict(d, path)
    except OSError:
        return None
    return latest


class ReplicaCursor:
    """One replica's exactly-once AUDIT watermark, persisted atomically
    as ``<dir>/replica-cursor.<replica_id>.json``.

    ``next_seq`` is the first log seq this replica identity has not yet
    journaled as applied — saved only after ``ModelRegistry.apply_delta``
    returns. It deliberately does NOT set where a rebooted replica starts
    applying: registry state is in-memory only, so every boot replays the
    log from 0 (or a snapshot marker) to rebuild it, journaling
    pre-cursor records as replays (``replication/tailer.py`` module doc).
    The cursor's job is lag accounting and keeping the per-seq
    ``replica_delta_applied`` audit rows exactly-once across
    incarnations. Saves fsync the temp file before the atomic replace,
    so a crash can never leave a cursor pointing past rows the journal
    never recorded."""

    def __init__(self, out_dir: str, replica_id: str):
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in str(replica_id))
        self.replica_id = str(replica_id)
        self.path = os.path.join(out_dir, f"replica-cursor.{safe}.json")
        os.makedirs(out_dir, exist_ok=True)

    def load(self) -> int:
        try:
            with open(self.path) as f:
                return int(json.load(f).get("next_seq", 0))
        except (OSError, ValueError):
            return 0

    def save(self, next_seq: int, applied_total: int = 0) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "next_seq": int(next_seq),
                "replica_id": self.replica_id,
                "applied_total": int(applied_total),
                "updated_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }, f)
            f.flush()
            os.fsync(f.fileno())    # content durable BEFORE the rename
        os.replace(tmp, self.path)  # atomic: never a torn cursor
        _fsync_dir(os.path.dirname(self.path) or ".")


class DeltaLogPublisher:
    """Online-trainer publisher writing to the durable delta log: the
    trainer publishes ONCE; every replica fans out by tailing. The
    publish-time trace id rides the record so each replica's apply span
    joins the trainer's publish span in the merged fleet timeline."""

    def __init__(self, path: str, snapshot_model_dir: Optional[str] = None):
        self.writer = DeltaLogWriter(path)
        # Base snapshot marker at log creation: a brand-new log's first
        # record tells late-joining replicas which full model dir is the
        # floor everything after builds on (the catch-up anchor).
        if snapshot_model_dir and self.writer.next_seq == 0:
            self.writer.append_snapshot(snapshot_model_dir, note="base")

    @property
    def path(self) -> str:
        return self.writer.path

    def publish(self, delta: ModelDelta) -> dict:
        from photon_tpu.obs import current_trace_id

        seq = self.writer.append(delta, trace_id=current_trace_id())
        return {"log_seq": seq, "log_path": self.writer.path}

    def close(self) -> None:
        self.writer.close()


class FanoutPublisher:
    """Compose publishers: the delta log AND a direct HTTP push during a
    migration window (each ``publish`` must succeed — the trainer's
    commit-after-publish contract covers them all)."""

    def __init__(self, *publishers):
        self.publishers = [p for p in publishers if p is not None]
        if not self.publishers:
            raise ValueError("FanoutPublisher needs >= 1 publisher")

    def publish(self, delta: ModelDelta) -> dict:
        out: dict = {}
        for p in self.publishers:
            r = p.publish(delta)
            if isinstance(r, dict):
                out.update(r)
        return out

    def close(self) -> None:
        for p in self.publishers:
            if hasattr(p, "close"):
                p.close()
