"""Replica consume loop: tail the delta log, apply exactly once, converge.

A :class:`ReplicaTailer` is the replication half of a serving replica
(``cli/serving_driver --delta-log``): it tails the durable delta log from
its persisted cursor and applies every record through the existing
``ModelRegistry.apply_delta`` path — the same validate-all-then-apply,
swap-lock-serialized route ``POST /admin/patch`` takes, so replication
and direct pushes can never interleave torn state.

Applied state is IN-MEMORY ONLY (the registry's coefficient overlay dies
with the process), so a (re)booting tailer always rebuilds it: replay
starts at seq 0 into the freshly loaded registry — or jumps straight to
the log's latest full-snapshot marker when the backlog exceeds
``catchup_lag`` — and only converges the watermark once the registry
really holds every logged delta. The persisted cursor deliberately does
NOT set the replay start: it is the exactly-once AUDIT watermark, the
first log seq this replica identity has not yet journaled as applied.
Records below it re-apply on rejoin (full-replacement patches make the
replay idempotent for coefficients) but are journaled as
``replica_delta_replayed``; records at/after it journal
``replica_delta_applied`` and advance the cursor (atomic replace) only
after ``apply_delta`` returns. Those per-apply rows — each log seq
exactly once across every incarnation of a replica id — are the audit
trail ``scripts/replica_smoke.py`` sums across the fleet.

Catch-up: when the boot backlog (log head − replay position) exceeds
``catchup_lag`` and the log holds a full-snapshot marker ahead of the
replay position, the tailer jumps — ``prepare_standby`` + ``swap`` to the
marker's model dir (PR 12's warm-standby machinery, so the swap is a
pointer move) and replay resumes at ``marker seq + 1``. No eligible
marker degrades to plain replay, which is always correct, just slower.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

from photon_tpu.obs import (
    REGISTRY as GLOBAL_REGISTRY,
    new_trace_id,
    trace_context,
    trace_span,
)
from photon_tpu.replication.log import (
    DeltaLogRecord,
    ReplicaCursor,
    find_latest_snapshot,
    iter_log,
    log_next_seq,
)


class ReplicaTailer:
    """Owns one replica's delta-log consumption (module doc)."""

    def __init__(
        self,
        registry,
        log_path: str,
        replica_id: Optional[str] = None,
        cursor_dir: Optional[str] = None,
        catchup_lag: int = 0,
        poll_s: float = 0.05,
        journal=None,
        logger=None,
        metrics=None,
    ):
        self.registry = registry
        self.log_path = log_path
        self.replica_id = str(replica_id or f"r{os.getpid()}")
        self.catchup_lag = int(catchup_lag)
        self.poll_s = float(poll_s)
        self.journal = journal
        self.logger = logger
        self.cursor = ReplicaCursor(
            cursor_dir or (os.path.dirname(log_path) or "."),
            self.replica_id)
        m = metrics if metrics is not None else GLOBAL_REGISTRY
        self._applied_c = m.counter(
            "replica_deltas_applied_total",
            "delta-log records applied by this replica")
        self._replayed_c = m.counter(
            "replica_deltas_replayed_total",
            "pre-cursor records re-applied at boot to rebuild in-memory "
            "state")
        self._dup_c = m.counter(
            "replica_duplicate_seqs_total",
            "delta-log records skipped as already-applied duplicates")
        self._catchup_c = m.counter(
            "replica_catchups_total",
            "snapshot catch-up jumps taken instead of full replay")
        self._error_c = m.counter(
            "replica_apply_errors_total",
            "delta-log records the registry refused")
        self._watermark_g = m.gauge(
            "replica_seq_watermark",
            "highest delta-log seq this replica has applied")
        self._lag_g = m.gauge(
            "replica_lag",
            "delta-log records between the log head and this replica")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self._lock = threading.Lock()
        self._applied_total = 0
        self._replayed_total = 0
        self._duplicates = 0
        self._catchups = 0
        self._last_applied_ts: Optional[float] = None
        self._last_error: Optional[str] = None
        self._refused = False   # a validation-refused delta poisons the log
        # The registry handed in was just rebuilt from its model dir: it
        # holds NONE of the deltas a previous incarnation applied (the
        # overlay is in-memory only), so replay starts at 0 regardless of
        # the persisted cursor — the cursor is the exactly-once JOURNAL
        # watermark, not the state watermark (module doc).
        self._next_seq = 0
        self._audit_next = self.cursor.load()
        self._stamp_gauges()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Tail in a background thread until :meth:`stop`."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._started = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run_follow,
            name=f"photon-replica-tail-{self.replica_id}",
            daemon=True,
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._started = False       # a deliberate stop is not a dead tailer
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def restart(self) -> dict:
        """Journaled restart request (``POST /admin/replication/restart``,
        the control plane's ``replication_tailer_dead`` remediation).

        A live follow thread makes this a no-op (``restarted: false``) —
        the lever is for the DEAD-tailer state, and an idempotent restart
        must not double-journal. A VALIDATION-refused delta also refuses
        to restart: the log itself is poisoned, so re-tailing would refuse
        again at the same seq — the error correctly keeps the replica
        drained until an operator intervenes. A transient follow-loop
        death clears the error and restarts the thread. Returns
        ``{"restarted", "snapshot"}``."""
        alive = self._thread is not None and self._thread.is_alive()
        if alive:
            return {"restarted": False, "snapshot": self.snapshot()}
        with self._lock:
            refused = self._refused
            err = self._last_error
            if not refused:
                self._last_error = None
        if refused:
            return {"restarted": False, "refused": True,
                    "snapshot": self.snapshot()}
        self._journal("replica_tailer_restarted",
                      prior_error=(err or "")[:200] or None)
        self.start()
        return {"restarted": True, "snapshot": self.snapshot()}

    def _run_follow(self) -> None:
        try:
            self._consume(follow=True)
        except Exception as e:  # noqa: BLE001 - surfaced on /healthz
            with self._lock:
                self._last_error = f"{type(e).__name__}: {e}"
            if self.logger is not None:
                self.logger.error("replica tailer died: %s", e)
            self._journal("replica_tailer_died",
                          error=f"{type(e).__name__}: {str(e)[:200]}")

    def run_once(self) -> int:
        """Synchronous drain to the current log head (tests, and the
        serving driver's boot: converge BEFORE the first health check
        reports a watermark). Returns the number of records applied."""
        return self._consume(follow=False)

    # -------------------------------------------------------------- consume

    def _consume(self, follow: bool) -> int:
        # A replica may boot before the publisher's first append creates
        # the log: wait for it under follow, no-op otherwise (the boot
        # drain has nothing to converge to yet).
        while not os.path.exists(self.log_path):
            if not follow or self._stop.is_set():
                self._stamp_gauges()
                return 0
            time.sleep(self.poll_s)
        self._maybe_catch_up()
        applied = 0
        records = iter_log(
            self.log_path,
            start_seq=self._next_seq,
            follow=follow,
            poll_s=self.poll_s,
            stop=self._stop.is_set,
            idle_yield_s=1.0 if follow else 0.0,
            on_duplicate=self._on_duplicate,
        )
        for rec in records:
            if rec is None:           # idle tick: refresh the lag gauge
                self._stamp_gauges()
                continue
            if rec.is_snapshot:
                # Reached sequentially, everything before it is already
                # applied — the marker is informational here; only a
                # catch-up JUMP builds from its model dir.
                self._advance(rec, applied_delta=False)
                continue
            self._apply(rec)
            applied += 1
        self._stamp_gauges()
        return applied

    def _apply(self, rec: DeltaLogRecord) -> None:
        # The publisher's trace id rides the log record; applying under it
        # joins this replica's apply span to the trainer's publish span in
        # the merged fleet timeline — the file-based analog of the
        # X-Photon-Trace-Id header on /admin/patch.
        with trace_context(rec.trace_id or new_trace_id()), \
                trace_span("replica.apply", cat="replication",
                           seq=rec.seq, replica=self.replica_id) as sp:
            try:
                result = self.registry.apply_delta(
                    rec.delta.raw_patches(),
                    seq=rec.delta.seq,
                    event_horizon=rec.delta.event_horizon,
                )
            except Exception as e:
                # A refused delta (validation) poisons every replica the
                # same way — record it and refuse to advance past it: a
                # cursor that skips a rejected record would diverge this
                # replica from the ones that applied it.
                self._error_c.inc(1, replica=self.replica_id)
                with self._lock:
                    self._last_error = f"{type(e).__name__}: {e}"
                    self._refused = True
                self._journal(
                    "replica_apply_refused", seq=rec.seq,
                    error=f"{type(e).__name__}: {str(e)[:200]}")
                raise
            sp.set(patch_seq=result["patch_seq"],
                   entities=result["patched"])
        self._advance(rec, applied_delta=True, result=result)

    def _advance(self, rec: DeltaLogRecord, applied_delta: bool,
                 result: Optional[dict] = None) -> None:
        # A record below the audit watermark is a boot-time REPLAY: a
        # previous incarnation already journaled it as applied, so it
        # rebuilds in-memory state but must not double-count in the
        # exactly-once audit, and the durable cursor never regresses.
        with self._lock:
            self._next_seq = rec.seq + 1
            replay = rec.seq < self._audit_next
            if not replay:
                self._audit_next = rec.seq + 1
            if applied_delta:
                if replay:
                    self._replayed_total += 1
                else:
                    self._applied_total += 1
                self._last_applied_ts = time.time()
            applied_total = self._applied_total
        if not replay:
            self.cursor.save(rec.seq + 1, applied_total=applied_total)
        if applied_delta:
            if replay:
                self._replayed_c.inc(1, replica=self.replica_id)
                self._journal("replica_delta_replayed", seq=rec.seq,
                              delta_seq=rec.delta.seq)
            else:
                self._applied_c.inc(1, replica=self.replica_id)
                self._journal(
                    "replica_delta_applied", seq=rec.seq,
                    delta_seq=rec.delta.seq,
                    patch_seq=(result or {}).get("patch_seq"),
                    entities=(result or {}).get("patched"),
                )
        self._stamp_gauges()

    def _on_duplicate(self, seq: int) -> None:
        with self._lock:
            self._duplicates += 1
        self._dup_c.inc(1, replica=self.replica_id)
        self._journal("replica_duplicate_seq", seq=seq)

    # ------------------------------------------------------------- catch-up

    def _maybe_catch_up(self) -> None:
        """Snapshot catch-up at (re)join time: when the replay backlog
        (log head − in-memory replay position) exceeds ``catchup_lag``
        and a full-snapshot marker sits at/ahead of that position, swap
        to it instead of replaying the whole backlog. At boot the replay
        position is 0, so ANY marker in the log is eligible — including
        the base marker a fresh log starts with."""
        if self.catchup_lag <= 0:
            return
        head = log_next_seq(self.log_path)
        lag = head - self._next_seq
        if lag <= self.catchup_lag:
            return
        marker = find_latest_snapshot(self.log_path,
                                      min_seq=self._next_seq)
        if marker is not None and marker.seq <= self._next_seq:
            # Jumping to a marker AT the replay position (e.g. the base
            # marker at seq 0 on a fresh boot) rebuilds nothing replay
            # wouldn't cover for free — skip the swap.
            marker = None
        if marker is None:
            if self.logger is not None:
                self.logger.info(
                    "replica %s lag %d exceeds catch-up threshold %d but "
                    "the log holds no snapshot marker ahead of seq %d; "
                    "replaying", self.replica_id, lag, self.catchup_lag,
                    self._next_seq)
            return
        model_dir = marker.snapshot["model_dir"]
        self._journal("replica_catchup_begin", lag=lag,
                      snapshot_seq=marker.seq, model_dir=model_dir)
        t0 = time.monotonic()
        with trace_span("replica.catchup", cat="replication",
                        replica=self.replica_id, snapshot_seq=marker.seq):
            # Warm off the hot path, then a pointer-move swap (PR 12).
            self.registry.prepare_standby(model_dir)
            self.registry.swap(model_dir)
        with self._lock:
            self._next_seq = marker.seq + 1
            # The jump covers every seq through the marker; the audit
            # watermark moves forward (never back — a jump below the
            # cursor is pure state rebuild, already journaled).
            self._audit_next = max(self._audit_next, marker.seq + 1)
            audit_next = self._audit_next
            self._catchups += 1
            applied_total = self._applied_total
        self.cursor.save(audit_next, applied_total=applied_total)
        self._catchup_c.inc(1, replica=self.replica_id)
        self._journal("replica_catchup_done", snapshot_seq=marker.seq,
                      seconds=round(time.monotonic() - t0, 3))
        if self.logger is not None:
            self.logger.info(
                "replica %s caught up via snapshot seq %d (%s); lag was %d",
                self.replica_id, marker.seq, model_dir, lag)
        self._stamp_gauges()

    # ------------------------------------------------------------ telemetry

    def _journal(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.record(event, replica=self.replica_id,
                                log_path=self.log_path, **fields)

    def _stamp_gauges(self) -> None:
        snap = self.snapshot()
        self._watermark_g.set(snap["seq_watermark"],
                              replica=self.replica_id)
        self._lag_g.set(snap["lag"], replica=self.replica_id)

    def snapshot(self) -> dict:
        """Replication state for ``/healthz`` and the metrics snapshot:
        watermark + lag are the staleness signal the router weights by."""
        head = log_next_seq(self.log_path)
        with self._lock:
            next_seq = self._next_seq
            out = {
                "replica_id": self.replica_id,
                "log_path": self.log_path,
                "seq_watermark": next_seq - 1,
                "next_seq": next_seq,
                "audit_next_seq": self._audit_next,
                "head_seq": head,
                "lag": max(0, head - next_seq),
                "applied_total": self._applied_total,
                "replayed_total": self._replayed_total,
                "duplicates_skipped": self._duplicates,
                "catchups": self._catchups,
                "last_applied_ts": self._last_applied_ts,
                "started": self._started,
                "running": (self._thread is not None
                            and self._thread.is_alive()),
                "error": self._last_error,
            }
        return out
