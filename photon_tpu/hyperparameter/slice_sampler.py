"""Slice sampler for GP hyperparameter posteriors.

Parity: reference ⟦photon-lib/.../hyperparameter/SliceSampler.scala⟧
(SURVEY.md §2.1): univariate slice sampling with step-out and shrinkage
(Neal 2003), applied coordinate-wise to the log-hyperparameter vector — the
same scheme Spearmint-style tuners and the reference use to integrate out GP
hyperparameters.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np


@dataclasses.dataclass
class SliceSampler:
    """Coordinate-wise slice sampling of an unnormalized log-density."""

    log_density: Callable[[np.ndarray], float]
    width: float = 1.0
    max_step_out: int = 8
    max_shrink: int = 32
    seed: int = 0

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)

    def _sample_coord(self, x: np.ndarray, i: int, logp_x: float) -> tuple[np.ndarray, float]:
        # Vertical slice: y ~ U(0, p(x)) → log y = log p(x) − Exp(1).
        log_y = logp_x - self._rng.exponential()
        # Step out.
        u = self._rng.uniform()
        lo = x[i] - self.width * u
        hi = lo + self.width
        for _ in range(self.max_step_out):
            if self._logp_at(x, i, lo) <= log_y:
                break
            lo -= self.width
        for _ in range(self.max_step_out):
            if self._logp_at(x, i, hi) <= log_y:
                break
            hi += self.width
        # Shrinkage.
        for _ in range(self.max_shrink):
            xi = self._rng.uniform(lo, hi)
            lp = self._logp_at(x, i, xi)
            if lp > log_y:
                x_new = x.copy()
                x_new[i] = xi
                return x_new, lp
            if xi < x[i]:
                lo = xi
            else:
                hi = xi
        return x, logp_x  # shrunk to nothing: keep the current point

    def _logp_at(self, x: np.ndarray, i: int, xi: float) -> float:
        x2 = x.copy()
        x2[i] = xi
        return self.log_density(x2)

    def sample(
        self, x0: np.ndarray, n_samples: int, n_burn: int = 0, thin: int = 1
    ) -> np.ndarray:
        """Draw ``n_samples`` states after ``n_burn`` burn-in sweeps."""
        x = np.asarray(x0, float).copy()
        logp = self.log_density(x)
        if not np.isfinite(logp):
            raise ValueError("slice sampler started at a zero-density point")
        out = []
        total = n_burn + n_samples * thin
        for it in range(total):
            for i in range(len(x)):
                x, logp = self._sample_coord(x, i, logp)
            if it >= n_burn and (it - n_burn) % thin == 0:
                out.append(x.copy())
        return np.stack(out)
