"""Gaussian-process surrogate model for Bayesian optimization.

Parity: reference ⟦photon-lib/.../hyperparameter/estimators/
GaussianProcessModel.scala, GaussianProcessEstimator.scala⟧ (SURVEY.md §2.1):
a GP posterior over the metric surface with kernel hyperparameters
(amplitude, lengthscales, noise) integrated out by **slice sampling** from
their posterior — predictions average over the sampled hyperparameter
settings, exactly the reference's estimator structure.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_tpu.hyperparameter.kernels import Matern52
from photon_tpu.hyperparameter.slice_sampler import SliceSampler


@dataclasses.dataclass(frozen=True)
class GaussianProcessModel:
    """Posterior of a zero-mean GP given observations (x, y) and a kernel.

    ``noise`` is observation-noise *variance* added to the diagonal.
    """

    x: np.ndarray          # [n, d]
    y: np.ndarray          # [n]
    kernel: object
    noise: float = 1e-6
    mean: float = 0.0      # constant prior mean (set to y.mean() by the fitter)

    def __post_init__(self):
        k = self.kernel(self.x, self.x)
        k[np.diag_indices_from(k)] += max(self.noise, 1e-10)
        chol = np.linalg.cholesky(k)
        alpha = np.linalg.solve(
            chol.T, np.linalg.solve(chol, self.y - self.mean)
        )
        object.__setattr__(self, "_chol", chol)
        object.__setattr__(self, "_alpha", alpha)

    def predict(self, xs: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Posterior (mean, variance) at query points [m, d]."""
        xs = np.atleast_2d(xs)
        ks = self.kernel(self.x, xs)            # [n, m]
        mu = self.mean + ks.T @ self._alpha
        v = np.linalg.solve(self._chol, ks)     # [n, m]
        kss = (
            self.kernel.diag(xs)
            if hasattr(self.kernel, "diag")
            else np.diag(self.kernel(xs, xs))
        )
        var = np.maximum(kss - np.sum(v * v, axis=0), 1e-12)
        return mu, var

    def log_marginal_likelihood(self) -> float:
        n = len(self.y)
        return float(
            -0.5 * (self.y - self.mean) @ self._alpha
            - np.sum(np.log(np.diag(self._chol)))
            - 0.5 * n * np.log(2.0 * np.pi)
        )


def _lml_for(theta: np.ndarray, x, y, kernel_cls, mean: float) -> float:
    """Log marginal likelihood + log-normal priors over θ = log(amp, noise,
    ℓ₁..ℓ_d) — the posterior the slice sampler explores (reference: priors on
    log-hyperparameters keep the sampler in sane ranges)."""
    amp, noise = np.exp(theta[0]), np.exp(theta[1])
    ls = np.exp(theta[2:])
    if amp > 1e3 or noise > 1e2 or np.any(ls > 1e3):
        return -np.inf
    try:
        m = GaussianProcessModel(x, y, kernel_cls(amp, ls), noise=noise, mean=mean)
    except np.linalg.LinAlgError:
        return -np.inf
    # N(0, 1) priors on log-params (weakly informative, as the reference's).
    return m.log_marginal_likelihood() - 0.5 * float(theta @ theta)


@dataclasses.dataclass
class GaussianProcessEstimator:
    """Fit GP hyperparameters by slice-sampling their posterior.

    ``fit(x, y)`` returns a list of GaussianProcessModel draws; predictions
    should average over them (``predict_mean_var``).
    """

    kernel_cls: type = Matern52
    n_samples: int = 8
    n_burn: int = 16
    seed: int = 0

    def fit(self, x: np.ndarray, y: np.ndarray) -> list[GaussianProcessModel]:
        x = np.atleast_2d(np.asarray(x, float))
        y = np.asarray(y, float)
        d = x.shape[1]
        mean = float(y.mean()) if len(y) else 0.0
        theta0 = np.zeros(2 + d)
        theta0[1] = np.log(max(1e-3, float(np.var(y)) * 0.01 + 1e-6))
        sampler = SliceSampler(
            lambda t: _lml_for(t, x, y, self.kernel_cls, mean), seed=self.seed
        )
        thetas = sampler.sample(theta0, self.n_samples, n_burn=self.n_burn)
        models = []
        for t in thetas:
            amp, noise = np.exp(t[0]), np.exp(t[1])
            ls = np.exp(t[2:])
            models.append(
                GaussianProcessModel(
                    x, y, self.kernel_cls(amp, ls), noise=noise, mean=mean
                )
            )
        return models


def predict_mean_var(
    models: Sequence[GaussianProcessModel], xs: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Average posterior over hyperparameter draws (law of total variance)."""
    mus, vars_ = zip(*(m.predict(xs) for m in models))
    mus = np.stack(mus)
    vars_ = np.stack(vars_)
    mu = mus.mean(axis=0)
    var = vars_.mean(axis=0) + mus.var(axis=0)
    return mu, var
