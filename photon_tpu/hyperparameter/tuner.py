"""GAME auto-tuning: Bayesian optimization of per-coordinate regularization.

Parity: the reference wires ⟦GaussianProcessSearch⟧ to ⟦GameEstimator⟧
through an EvaluationFunction that trains one GAME model per proposed
hyperparameter vector and returns the validation metric (SURVEY.md §6 config
(4): "GAME per-user + per-item random effects CTR with Bayesian
hyperparameter auto-tuning").

Parameters are named ``<coordinateId>.reg_weight``; log scale is the correct
default for regularization weights.
"""
from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_tpu.estimators import (
    GameEstimator,
    GameOptimizationConfiguration,
    reg_weight_sweep,
)
from photon_tpu.estimators.game_estimator import GameFitResult
from photon_tpu.evaluation import EvaluationSuite
from photon_tpu.hyperparameter.rescaling import ParamRange, VectorRescaling
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
    SearchResult,
)
from photon_tpu.io.data_reader import GameDataBundle


@dataclasses.dataclass(frozen=True)
class TuningResult:
    search: SearchResult
    best_config: GameOptimizationConfiguration
    # The fully trained result for the best configuration — already fitted
    # during the search; no refit needed.
    best_result: Optional[GameFitResult] = None

    @property
    def best_params(self) -> np.ndarray:
        return self.search.best_point


def tune_regularization(
    estimator: GameEstimator,
    train: GameDataBundle,
    validation: GameDataBundle,
    base_config: GameOptimizationConfiguration,
    reg_ranges: Mapping[str, tuple[float, float]],
    n_iterations: int = 10,
    strategy: str = "gp",
    seed: int = 0,
    initial_model=None,
) -> TuningResult:
    """Search per-coordinate reg weights; returns history + best config.

    ``reg_ranges``: coordinate id → (min, max) reg weight, searched on log
    scale. The objective is the estimator's primary evaluator on validation
    (negated internally when bigger is better — searches minimize).
    """
    if not estimator.evaluator_specs:
        raise ValueError("estimator needs evaluator_specs for tuning")
    suite = EvaluationSuite.parse(estimator.evaluator_specs)
    sign = -1.0 if suite.primary.bigger_is_better else 1.0

    cids = sorted(reg_ranges)
    rescaling = VectorRescaling(
        [
            ParamRange(f"{cid}.reg_weight", lo, hi, scale="log")
            for cid, (lo, hi) in ((c, reg_ranges[c]) for c in cids)
        ]
    )

    def config_for(vec: np.ndarray) -> GameOptimizationConfiguration:
        # Singleton-axis sweep expansion — shares reg_weight_sweep's
        # validation and construction (one config out).
        return reg_weight_sweep(
            base_config, {cid: [float(w)] for cid, w in zip(cids, vec)}
        )[0]

    best: dict = {"value": np.inf, "result": None}

    def evaluate(vec: np.ndarray) -> float:
        result = estimator.fit(
            train, validation, [config_for(vec)], initial_model=initial_model
        )[0]
        v = sign * result.evaluation.primary
        if v < best["value"]:
            best["value"] = v
            best["result"] = result
        return v

    if strategy == "gp":
        search = GaussianProcessSearch(rescaling, seed=seed)
    elif strategy == "random":
        search = RandomSearch(rescaling, seed=seed)
    else:
        raise ValueError(f"strategy must be 'gp' or 'random', got {strategy!r}")
    history = search.search(evaluate, n_iterations)
    return TuningResult(
        search=history,
        best_config=config_for(history.best_point),
        best_result=best["result"],
    )
