"""GAME auto-tuning: Bayesian optimization of per-coordinate regularization.

Parity: the reference wires ⟦GaussianProcessSearch⟧ to ⟦GameEstimator⟧
through an EvaluationFunction that trains one GAME model per proposed
hyperparameter vector and returns the validation metric (SURVEY.md §6 config
(4): "GAME per-user + per-item random effects CTR with Bayesian
hyperparameter auto-tuning").

Parameters are named ``<coordinateId>.reg_weight``; log scale is the correct
default for regularization weights.
"""
from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_tpu.estimators import (
    GameEstimator,
    GameOptimizationConfiguration,
    reg_weight_sweep,
)
from photon_tpu.estimators.game_estimator import GameFitResult
from photon_tpu.evaluation import EvaluationSuite
from photon_tpu.hyperparameter.rescaling import ParamRange, VectorRescaling
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
    SearchResult,
)
from photon_tpu.io.data_reader import GameDataBundle


@dataclasses.dataclass(frozen=True)
class TuningResult:
    search: SearchResult
    best_config: GameOptimizationConfiguration
    # The fully trained result for the best configuration. Usually the model
    # fitted during the search; when the best trial predates a checkpoint
    # resume, tune_regularization refits it once (deterministically).
    best_result: Optional[GameFitResult] = None

    @property
    def best_params(self) -> np.ndarray:
        return self.search.best_point


def tune_regularization(
    estimator: GameEstimator,
    train: GameDataBundle,
    validation: GameDataBundle,
    base_config: GameOptimizationConfiguration,
    reg_ranges: Mapping[str, tuple[float, float]],
    n_iterations: int = 10,
    strategy: str = "gp",
    seed: int = 0,
    initial_model=None,
    checkpoint_manager=None,
) -> TuningResult:
    """Search per-coordinate reg weights; returns history + best config.

    ``reg_ranges``: coordinate id → (min, max) reg weight, searched on log
    scale. The objective is the estimator's primary evaluator on validation
    (negated internally when bigger is better — searches minimize).

    ``checkpoint_manager`` (photon_tpu.checkpoint.CheckpointManager) enables
    TRIAL-level checkpoint/resume: the search state (evaluated trials, PRNG
    state, pending proposals) snapshots after every trial, and a restarted
    call with the same arguments fast-forwards past completed trials and
    continues with exactly the trials the uninterrupted run would have
    evaluated (bit-identical history; a mismatched configuration is
    refused). The per-trial model refits only if the best trial predates
    the resume point.
    """
    if not estimator.evaluator_specs:
        raise ValueError("estimator needs evaluator_specs for tuning")
    suite = EvaluationSuite.parse(estimator.evaluator_specs)
    sign = -1.0 if suite.primary.bigger_is_better else 1.0

    cids = sorted(reg_ranges)
    rescaling = VectorRescaling(
        [
            ParamRange(f"{cid}.reg_weight", lo, hi, scale="log")
            for cid, (lo, hi) in ((c, reg_ranges[c]) for c in cids)
        ]
    )

    def config_for(vec: np.ndarray) -> GameOptimizationConfiguration:
        # Singleton-axis sweep expansion — shares reg_weight_sweep's
        # validation and construction (one config out).
        return reg_weight_sweep(
            base_config, {cid: [float(w)] for cid, w in zip(cids, vec)}
        )[0]

    best: dict = {"value": np.inf, "result": None}

    def evaluate(vec: np.ndarray) -> float:
        result = estimator.fit(
            train, validation, [config_for(vec)], initial_model=initial_model
        )[0]
        v = sign * result.evaluation.primary
        if v < best["value"]:
            best["value"] = v
            best["result"] = result
        return v

    if strategy == "gp":
        search = GaussianProcessSearch(rescaling, seed=seed)
    elif strategy == "random":
        search = RandomSearch(rescaling, seed=seed)
    else:
        raise ValueError(f"strategy must be 'gp' or 'random', got {strategy!r}")

    resume_state, on_trial = None, None
    if checkpoint_manager is not None:
        from photon_tpu.checkpoint import run_fingerprint

        fingerprint = run_fingerprint((
            "tuning", sorted(reg_ranges.items()), n_iterations, strategy,
            seed, repr(base_config), estimator.fingerprint_parts(),
        ))
        payload = checkpoint_manager.load_checked("tuning", fingerprint)
        if payload is not None:
            resume_state = payload["state"]

        def on_trial(state, trial_index):
            checkpoint_manager.save(
                trial_index, state,
                {"kind": "tuning", "fingerprint": fingerprint},
            )

    history = search.search(
        evaluate, n_iterations, state=resume_state, on_trial=on_trial
    )
    if best["result"] is None or sign * best["result"].evaluation.primary \
            > history.best_value:
        # The best trial predates the resume point; one deterministic refit
        # reproduces its model.
        best["result"] = estimator.fit(
            train, validation, [config_for(history.best_point)],
            initial_model=initial_model,
        )[0]
    return TuningResult(
        search=history,
        best_config=config_for(history.best_point),
        best_result=best["result"],
    )
