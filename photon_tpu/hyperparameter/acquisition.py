"""Acquisition functions for Bayesian optimization.

Parity: reference ⟦photon-lib/.../hyperparameter/ExpectedImprovement.scala⟧
(SURVEY.md §2.1): expected improvement over the incumbent for a
*minimization* problem (the reference minimizes its evaluation function;
callers negate bigger-is-better metrics).
"""
from __future__ import annotations

import numpy as np
from scipy import special


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / np.sqrt(2.0 * np.pi)


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + special.erf(z / np.sqrt(2.0)))


def expected_improvement(
    mu: np.ndarray, var: np.ndarray, best: float, xi: float = 0.0
) -> np.ndarray:
    """EI(x) = E[max(best − ξ − f(x), 0)] for minimization.

    ``mu``/``var`` are the surrogate posterior at candidate points; ``best``
    is the incumbent (lowest observed value); ``xi`` trades off exploration.
    """
    sigma = np.sqrt(np.maximum(var, 0.0))
    imp = best - xi - mu
    safe = np.where(sigma > 0.0, sigma, 1.0)
    z = imp / safe
    ei = imp * _norm_cdf(z) + sigma * _norm_pdf(z)
    # Zero-uncertainty candidates degenerate to the deterministic improvement.
    return np.where(sigma > 0.0, ei, np.maximum(imp, 0.0))
