"""Hyperparameter range definitions and [0,1]^d rescaling.

Parity: reference ⟦photon-lib/.../hyperparameter/VectorRescaling.scala,
HyperparameterSerialization.scala⟧ (SURVEY.md §2.1): search ranges declared
per parameter with linear or log scale, mapped to the unit cube for the GP
(kernel lengthscales are meaningful only on normalized axes), and back to
native units for evaluation. JSON (de)serialization of the range config.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class ParamRange:
    """One tunable parameter: name + [min, max] + scale ('linear'|'log')."""

    name: str
    min: float
    max: float
    scale: str = "linear"

    def __post_init__(self):
        if self.scale not in ("linear", "log"):
            raise ValueError(f"{self.name}: scale must be linear|log, got {self.scale}")
        if not (self.max > self.min):
            raise ValueError(f"{self.name}: need max > min")
        if self.scale == "log" and self.min <= 0:
            raise ValueError(f"{self.name}: log scale needs min > 0")


@dataclasses.dataclass(frozen=True)
class VectorRescaling:
    """Map native parameter vectors ↔ the unit cube."""

    ranges: Sequence[ParamRange]

    @property
    def dim(self) -> int:
        return len(self.ranges)

    @property
    def names(self) -> list[str]:
        return [r.name for r in self.ranges]

    def to_unit(self, x: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(np.asarray(x, float))
        out = np.empty_like(x)
        for j, r in enumerate(self.ranges):
            if r.scale == "log":
                out[:, j] = (np.log(x[:, j]) - np.log(r.min)) / (
                    np.log(r.max) - np.log(r.min)
                )
            else:
                out[:, j] = (x[:, j] - r.min) / (r.max - r.min)
        return out

    def from_unit(self, u: np.ndarray) -> np.ndarray:
        u = np.clip(np.atleast_2d(np.asarray(u, float)), 0.0, 1.0)
        out = np.empty_like(u)
        for j, r in enumerate(self.ranges):
            if r.scale == "log":
                out[:, j] = np.exp(
                    np.log(r.min) + u[:, j] * (np.log(r.max) - np.log(r.min))
                )
            else:
                out[:, j] = r.min + u[:, j] * (r.max - r.min)
        return out

    def sample(self, rng: np.random.Generator, n: int) -> np.ndarray:
        """n native-unit samples uniform in the (scaled) cube."""
        return self.from_unit(rng.random((n, self.dim)))


def ranges_to_json(ranges: Sequence[ParamRange]) -> str:
    return json.dumps(
        {
            "variables": [
                {"name": r.name, "min": r.min, "max": r.max, "scale": r.scale}
                for r in ranges
            ]
        },
        indent=2,
    )


def ranges_from_json(text: str) -> list[ParamRange]:
    """Parse the reference-style JSON range config:
    {"variables": [{"name", "min", "max", "scale"?}, ...]}."""
    obj = json.loads(text)
    if "variables" not in obj:
        raise ValueError("hyperparameter config needs a 'variables' list")
    out = []
    for v in obj["variables"]:
        out.append(
            ParamRange(
                name=v["name"],
                min=float(v["min"]),
                max=float(v["max"]),
                scale=v.get("scale", "linear"),
            )
        )
    return out
