"""Search strategies: pure random and GP-guided Bayesian optimization.

Parity: reference ⟦photon-lib/.../hyperparameter/search/RandomSearch.scala,
GaussianProcessSearch.scala, EvaluationFunction.scala⟧ (SURVEY.md §2.1): an
``EvaluationFunction`` maps a native-unit parameter vector to a scalar to
**minimize**; searches propose, evaluate, observe, repeat, and return the full
history. GaussianProcessSearch seeds with random points, then maximizes
Expected Improvement over a random candidate pool under the slice-sampled GP
posterior — the reference's exact loop, minus Spark plumbing.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional, Sequence

import numpy as np

from photon_tpu.hyperparameter.acquisition import expected_improvement
from photon_tpu.hyperparameter.gp import (
    GaussianProcessEstimator,
    predict_mean_var,
)
from photon_tpu.hyperparameter.kernels import Matern52
from photon_tpu.hyperparameter.rescaling import VectorRescaling

logger = logging.getLogger("photon_tpu.hyperparameter")

# vector (native units) -> value to minimize
EvaluationFunction = Callable[[np.ndarray], float]


@dataclasses.dataclass(frozen=True)
class SearchResult:
    """Full history + incumbent."""

    points: np.ndarray     # [n, d] native units
    values: np.ndarray     # [n]

    @property
    def best_index(self) -> int:
        return int(np.argmin(self.values))

    @property
    def best_point(self) -> np.ndarray:
        return self.points[self.best_index]

    @property
    def best_value(self) -> float:
        return float(self.values[self.best_index])


# Trial-level search state for checkpoint/resume: everything the loop needs
# to continue exactly where it stopped — evaluated trials, the PRNG state,
# and proposals already drawn but not yet evaluated (so a resumed run
# evaluates the very same next point the uninterrupted run would have).
def _trial_state(pts, vals, rng, queue) -> dict:
    return {
        "points": [np.asarray(p) for p in pts],
        "values": [float(v) for v in vals],
        "rng_state": rng.bit_generator.state,
        "queue": [np.asarray(q) for q in queue],
    }


def _restore(state, rng, pts, vals, queue) -> None:
    pts.extend(np.asarray(p) for p in state["points"])
    vals.extend(float(v) for v in state["values"])
    queue.extend(np.asarray(q) for q in state["queue"])
    rng.bit_generator.state = state["rng_state"]


@dataclasses.dataclass
class RandomSearch:
    """Uniform search in the (scaled) range cube — reference ⟦RandomSearch⟧."""

    rescaling: VectorRescaling
    seed: int = 0

    def search(
        self,
        evaluate: EvaluationFunction,
        n: int,
        state: Optional[dict] = None,
        on_trial=None,
    ) -> SearchResult:
        rng = np.random.default_rng(self.seed)
        pts: list[np.ndarray] = []
        vals: list[float] = []
        queue: list[np.ndarray] = []
        if state is not None:
            _restore(state, rng, pts, vals, queue)
        deficit = n - len(pts) - len(queue)
        if deficit > 0:
            # Fresh start, or a resume asked for MORE trials than the saved
            # run: draw the shortfall from the restored generator (the
            # stream continues deterministically either way).
            queue.extend(self.rescaling.sample(rng, deficit))
        while len(pts) < n and queue:
            p = queue.pop(0)
            vals.append(float(evaluate(p)))
            pts.append(p)
            if on_trial is not None:
                on_trial(_trial_state(pts, vals, rng, queue), len(pts))
        points = (np.stack(pts) if pts
                  else np.zeros((0, self.rescaling.dim)))
        return SearchResult(points, np.asarray(vals, float))


@dataclasses.dataclass
class GaussianProcessSearch:
    """Sequential Bayesian optimization — reference ⟦GaussianProcessSearch⟧.

    ``n_seed`` random evaluations, then per iteration: slice-sample GP
    hyperparameters on the unit-cube observations, score a random candidate
    pool with Expected Improvement, evaluate the argmax.
    Prior observations can be injected with ``observe`` (the reference's
    warm-start from past sweeps).
    """

    rescaling: VectorRescaling
    n_seed: int = 3
    n_candidates: int = 512
    kernel_cls: type = Matern52
    n_gp_samples: int = 6
    seed: int = 0

    def __post_init__(self):
        self._obs_u: list[np.ndarray] = []
        self._obs_y: list[float] = []

    def observe(self, point_native: np.ndarray, value: float) -> None:
        self._obs_u.append(self.rescaling.to_unit(point_native)[0])
        self._obs_y.append(float(value))

    def search(
        self,
        evaluate: EvaluationFunction,
        n: int,
        state: Optional[dict] = None,
        on_trial=None,
    ) -> SearchResult:
        """``state``/``on_trial`` give trial-level checkpoint/resume: every
        completed trial calls ``on_trial(search_state, trial_index)``; a run
        restarted with the last saved state replays the history into the GP,
        restores the PRNG, and evaluates exactly the trials the
        uninterrupted run would have (bit-identical result — tested)."""
        rng = np.random.default_rng(self.seed)
        pts: list[np.ndarray] = []
        vals: list[float] = []
        queue: list[np.ndarray] = []

        if state is not None:
            _restore(state, rng, pts, vals, queue)
            # Warm-start observations injected via observe() before the
            # crashed run are part of the GP posterior; restore them BEFORE
            # replaying trial observations or the resumed proposals diverge.
            self._obs_u = [np.asarray(u) for u in state.get("pre_obs_u", [])]
            self._obs_y = [float(y) for y in state.get("pre_obs_y", [])]
            for p, v in zip(pts, vals):
                self.observe(p, v)
        pre_obs_u = [np.asarray(u) for u in self._obs_u[: len(self._obs_u)
                                                        - len(pts)]]
        pre_obs_y = [float(y) for y in self._obs_y[: len(self._obs_y)
                                                   - len(pts)]]

        def run(native: np.ndarray) -> None:
            v = float(evaluate(native))
            pts.append(native)
            vals.append(v)
            self.observe(native, v)
            logger.info(
                "hyperparameter eval %d: %s -> %.6g",
                len(pts), np.array2string(native, precision=4), v,
            )
            if on_trial is not None:
                s = _trial_state(pts, vals, rng, queue)
                s["pre_obs_u"] = pre_obs_u
                s["pre_obs_y"] = pre_obs_y
                on_trial(s, len(pts))

        if state is None:
            n_seed = min(self.n_seed, n) if not self._obs_y else min(
                max(0, self.n_seed - len(self._obs_y)), n
            )
            queue.extend(self.rescaling.sample(rng, n_seed))

        while len(pts) < n:
            while queue and len(pts) < n:
                run(queue.pop(0))
            if len(pts) >= n:
                break
            u = np.asarray(self._obs_u, float)
            y = np.asarray(self._obs_y, float)
            # Standardize observations for the GP (zero mean unit variance).
            y_std = float(y.std()) or 1.0
            y_n = (y - y.mean()) / y_std
            models = GaussianProcessEstimator(
                kernel_cls=self.kernel_cls,
                n_samples=self.n_gp_samples,
                seed=int(rng.integers(2**31)),
            ).fit(u, y_n)
            cand = rng.random((self.n_candidates, self.rescaling.dim))
            mu, var = predict_mean_var(models, cand)
            ei = expected_improvement(mu, var, best=float(y_n.min()))
            queue.append(
                self.rescaling.from_unit(cand[int(np.argmax(ei))][None, :])[0]
            )

        points = (np.stack(pts) if pts
                  else np.zeros((0, self.rescaling.dim)))
        return SearchResult(points, np.asarray(vals, float))
