"""GP covariance kernels: RBF and Matérn-5/2 with ARD lengthscales.

Parity: reference ⟦photon-lib/.../hyperparameter/estimators/kernels/
RBF.scala, Matern52.scala⟧ (SURVEY.md §2.1 "Hyperparameter tuning"): both
kernels carry an amplitude and per-dimension lengthscales; the reference adds
the observation-noise variance at the GP level, as does this port.

Host-side numpy: the GP fits over dozens of points — device offload would be
pure overhead.
"""
from __future__ import annotations

import dataclasses

import numpy as np


def _sq_dists(x1: np.ndarray, x2: np.ndarray, ls: np.ndarray) -> np.ndarray:
    a = x1 / ls
    b = x2 / ls
    return (
        np.sum(a * a, axis=1)[:, None]
        + np.sum(b * b, axis=1)[None, :]
        - 2.0 * a @ b.T
    ).clip(min=0.0)


@dataclasses.dataclass(frozen=True)
class RBF:
    """k(x, x') = amp² · exp(−½‖(x−x')/ℓ‖²)."""

    amplitude: float = 1.0
    lengthscales: np.ndarray = None  # [d] or scalar broadcast

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        ls = np.asarray(self.lengthscales if self.lengthscales is not None else 1.0)
        d2 = _sq_dists(np.atleast_2d(x1), np.atleast_2d(x2), ls)
        return self.amplitude**2 * np.exp(-0.5 * d2)

    def diag(self, xs: np.ndarray) -> np.ndarray:
        """k(x, x) per row — constant amp² for stationary kernels (avoids the
        m×m matrix in the acquisition hot path)."""
        return np.full(np.atleast_2d(xs).shape[0], self.amplitude**2)

    def with_params(self, amplitude: float, lengthscales) -> "RBF":
        return RBF(amplitude, np.asarray(lengthscales, float))


@dataclasses.dataclass(frozen=True)
class Matern52:
    """k(r) = amp² · (1 + √5 r + 5r²/3) exp(−√5 r), r = ‖(x−x')/ℓ‖.

    The reference's default kernel for Bayesian optimization (twice
    differentiable but less smooth than RBF — better for noisy metric
    surfaces)."""

    amplitude: float = 1.0
    lengthscales: np.ndarray = None

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        ls = np.asarray(self.lengthscales if self.lengthscales is not None else 1.0)
        r = np.sqrt(_sq_dists(np.atleast_2d(x1), np.atleast_2d(x2), ls))
        s5r = np.sqrt(5.0) * r
        return self.amplitude**2 * (1.0 + s5r + s5r**2 / 3.0) * np.exp(-s5r)

    def diag(self, xs: np.ndarray) -> np.ndarray:
        return np.full(np.atleast_2d(xs).shape[0], self.amplitude**2)

    def with_params(self, amplitude: float, lengthscales) -> "Matern52":
        return Matern52(amplitude, np.asarray(lengthscales, float))


KERNELS = {"rbf": RBF, "matern52": Matern52}
