"""Hyperparameter tuning — reference ⟦photon-lib/.../hyperparameter⟧
(SURVEY.md §1 H, §2.1): GP surrogate (Matérn-5/2 / RBF), Expected
Improvement, slice-sampled GP hyperparameters, random search, range
rescaling/serialization, and the GAME reg-weight tuner."""
from photon_tpu.hyperparameter.acquisition import expected_improvement
from photon_tpu.hyperparameter.gp import (
    GaussianProcessEstimator,
    GaussianProcessModel,
    predict_mean_var,
)
from photon_tpu.hyperparameter.kernels import KERNELS, Matern52, RBF
from photon_tpu.hyperparameter.rescaling import (
    ParamRange,
    VectorRescaling,
    ranges_from_json,
    ranges_to_json,
)
from photon_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
    SearchResult,
)
from photon_tpu.hyperparameter.slice_sampler import SliceSampler
from photon_tpu.hyperparameter.tuner import TuningResult, tune_regularization

__all__ = [
    "expected_improvement",
    "GaussianProcessEstimator",
    "GaussianProcessModel",
    "predict_mean_var",
    "KERNELS",
    "Matern52",
    "RBF",
    "ParamRange",
    "VectorRescaling",
    "ranges_from_json",
    "ranges_to_json",
    "GaussianProcessSearch",
    "RandomSearch",
    "SearchResult",
    "SliceSampler",
    "TuningResult",
    "tune_regularization",
]
