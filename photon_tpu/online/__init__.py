"""Online incremental learning: streaming per-entity refresh → serving delta.

Upstream photon-ml can only batch-retrain GAME models — ``GameTrainingDriver``
re-runs full coordinate descent over Spark RDDs, so score freshness is
bounded by the retrain cadence (PAPER.md §0). This subsystem closes the loop
the rest of the stack is already positioned for (ROADMAP item 3):

* **events** — a durable JSONL event log (one labeled observation per line,
  monotone ``seq``), a replay cursor for restart-exact resume, and the
  feature resolver that turns an event's (bag, name, term, value) lists into
  fixed-width ELL rows through the SAME index maps training used.
* **state** — per-entity sliding windows (the data each refresh re-solves
  on), the dirty set (entities with events since their last refresh, oldest
  first), and the trainer's posterior state (means + variances per entity —
  the anchor for the next refresh's :class:`PriorDistribution`).
* **trainer** — :class:`OnlineTrainer`: consumes the stream (optionally via
  ``io/prefetch.prefetch``), marks entities dirty as events arrive, and on a
  cadence re-solves dirty entities in micro-batches through the blessed
  chunk-ladder Newton kernels (``game/newton_re.py``), each refresh anchored
  to the entity's previous posterior. Mid-refresh device loss recovers
  in-run (PR 8 contract): clear executable caches, re-run bit-identically,
  bounded by ``PHOTON_DEVICE_LOST_MAX_RECOVERIES``.
* **delta** — publication is by MODEL DELTA: changed-entity coefficient
  patches (never full snapshots), applied atomically to the serving
  coefficient store + registry (``ModelRegistry.apply_delta``) with the
  device LRU hot-set invalidated only for patched entities; a versioned
  patch journal records every published delta.

Publishers: :class:`RegistryPublisher` (in-process, the bench/test path)
and :class:`HttpPublisher` (``POST /admin/patch`` against a live scoring
server — the cross-process deployment shape). docs/online.md is the
operator-facing walkthrough (event schema, dirty-set semantics, the
delta-publish protocol, freshness SLOs).
"""
from photon_tpu.online.delta import (
    EntityPatch,
    ModelDelta,
    PatchJournal,
)
from photon_tpu.online.events import (
    EventCursor,
    EventError,
    EventWriter,
    OnlineEvent,
    append_events,
    iter_events,
    resolve_event_features,
)
from photon_tpu.online.state import EntityWindows, OnlineModelState

# trainer names resolve lazily (PEP 562): importing the trainer module
# builds an OnlineTrainerConfig default, which reaches the jax-backed
# Newton kernels — an import cost (and a hard jax dependency) that the
# jax-free consumers of this package (replication/log's ModelDelta use,
# the router and control drivers) must not pay.
_TRAINER_EXPORTS = (
    "HttpPublisher",
    "OnlineCoordinate",
    "OnlineTrainer",
    "OnlineTrainerConfig",
    "RegistryPublisher",
)


def __getattr__(name: str):
    if name in _TRAINER_EXPORTS:
        from photon_tpu.online import trainer

        return getattr(trainer, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "EntityPatch",
    "ModelDelta",
    "PatchJournal",
    "EventCursor",
    "EventError",
    "EventWriter",
    "OnlineEvent",
    "append_events",
    "iter_events",
    "resolve_event_features",
    "EntityWindows",
    "OnlineModelState",
    "HttpPublisher",
    "OnlineCoordinate",
    "OnlineTrainer",
    "OnlineTrainerConfig",
    "RegistryPublisher",
]
