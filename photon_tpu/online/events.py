"""Online event log: JSONL schema, durable append, replay cursor, resolver.

One event = one labeled observation (docs/online.md §"Event schema"):

    {"seq": 17, "ts": 1754300000.1,
     "entities": {"userId": "u3"},
     "features": [{"name": "c", "term": "4", "value": 1.2}],
     "label": 1.0, "offset": 0.0, "weight": 1.0}

``features`` is either a flat list (the default ``features`` bag) or a map
of bag → list, mirroring the training records' feature-bag fields and the
serving request schema — the three ingest surfaces stay one dialect.
``seq`` is assigned monotonically by the writer; the replay cursor persists
``next_seq`` so a restarted trainer resumes exactly where it stopped
(events below the cursor were fully refreshed AND published — the cursor
only advances after a successful delta publish).

Appends go through the same O_APPEND whole-line discipline as
``utils/logging.write_metrics_jsonl`` (each line written in one syscall),
so a concurrent producer and a tailing trainer never see a torn line; the
reader side treats an unterminated final line as "not yet written" and
(under ``follow=True``) waits for the rest.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable, Iterator, Mapping, Optional, Sequence

import numpy as np

logger = logging.getLogger("photon_tpu.online")


class EventError(ValueError):
    """A malformed event (bad schema, over-cap features) — the producer's
    bug, reported per event so one bad record never kills the stream."""


@dataclasses.dataclass(frozen=True)
class OnlineEvent:
    """One labeled observation on the stream."""

    entities: Mapping[str, str]          # re_type -> entity key
    features: Mapping[str, Sequence]     # bag -> [{"name","term","value"}]
    label: float
    offset: float = 0.0
    weight: float = 1.0
    ts: float = 0.0                      # producer timestamp (epoch seconds)
    seq: int = -1                        # assigned by the writer

    def __post_init__(self):
        if isinstance(self.features, (list, tuple)):
            # A flat list means the default "features" bag, as on the wire.
            object.__setattr__(self, "features",
                               {"features": list(self.features)})

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "ts": self.ts,
            "entities": dict(self.entities),
            "features": {k: list(v) for k, v in self.features.items()},
            "label": self.label,
            "offset": self.offset,
            "weight": self.weight,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OnlineEvent":
        if not isinstance(d, dict):
            raise EventError(f"event must be a JSON object, got {type(d)}")
        feats = d.get("features") or {}
        if isinstance(feats, (list, tuple)):
            feats = {"features": list(feats)}  # flat list = default bag
        if not isinstance(feats, dict):
            raise EventError('"features" must be a list or a bag map')
        entities = d.get("entities") or {}
        if not isinstance(entities, dict):
            raise EventError('"entities" must be a map of RE type -> id')
        if "label" not in d:
            raise EventError('event missing required "label"')
        try:
            return cls(
                entities={str(k): str(v) for k, v in entities.items()},
                features=feats,
                label=float(d["label"]),
                offset=float(d.get("offset") or 0.0),
                weight=float(d.get("weight", 1.0)),
                ts=float(d.get("ts") or 0.0),
                seq=int(d.get("seq", -1)),
            )
        except (TypeError, ValueError) as e:
            raise EventError(f"bad event field: {e}") from None


class EventWriter:
    """Durable JSONL appender assigning monotone ``seq``.

    Each event lands as ONE ``os.write`` of a full line on an O_APPEND fd —
    the same whole-line-atomic contract as ``write_metrics_jsonl`` (no
    rotation here: the event log is the replay substrate and ``seq`` is the
    cursor's coordinate system). Resuming an existing log continues the
    sequence from the last recorded ``seq``.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._next_seq = _tail_next_seq(path)
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def append(self, event: OnlineEvent) -> int:
        """Write one event; returns its assigned ``seq``."""
        seq = self._next_seq
        self._next_seq += 1
        d = event.to_dict()
        d["seq"] = seq
        if not d["ts"]:
            d["ts"] = time.time()
        os.write(self._fd, (json.dumps(d) + "\n").encode("utf-8"))
        return seq

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "EventWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _tail_next_seq(path: str, window: int = 1 << 16) -> int:
    """``last complete line's seq + 1`` by reading only the file TAIL
    (seqs are monotone, so the last line suffices — a full-log parse per
    writer open would make repeated ``append_events`` batches O(n²)).
    Falls back to a full scan only when the final ``window`` bytes hold no
    complete line (pathologically long records)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size == 0:
        return 0
    with open(path, "rb") as f:
        f.seek(max(0, size - window))
        tail = f.read()
    # Drop a torn final line (write in flight / crashed writer): its seq
    # was never durably published, and the reader skips it too.
    complete = tail[: tail.rfind(b"\n") + 1] if b"\n" in tail else b""
    lines = [x for x in complete.split(b"\n") if x.strip()]
    if lines:
        # Lines before the first newline of a mid-file window may be
        # partial — walk from the END, where lines are whole.
        for raw in reversed(lines):
            try:
                return int(json.loads(raw).get("seq", -1)) + 1
            except (ValueError, AttributeError, TypeError):
                continue
    # No parseable line in the window: full scan (rare, loud to stay safe).
    next_seq = 0
    for ev in iter_events(path):
        next_seq = max(next_seq, ev.seq + 1)
    return next_seq


def append_events(path: str, events: Sequence[OnlineEvent]) -> int:
    """One-shot append; returns the first assigned seq."""
    with EventWriter(path) as w:
        first = w.next_seq
        for ev in events:
            w.append(ev)
    return first


def iter_events(
    path: str,
    start_seq: int = 0,
    follow: bool = False,
    poll_s: float = 0.05,
    stop: Optional[Callable[[], bool]] = None,
    idle_yield_s: float = 0.0,
) -> Iterator[OnlineEvent]:
    """Replay events with ``seq >= start_seq``; ``follow=True`` tails the
    log (polling) until ``stop()`` returns true.

    ``idle_yield_s > 0`` (follow mode) yields ``None`` after that long
    without a new event — an IDLE TICK, so a consumer driving a refresh
    cadence (``OnlineTrainer.run``) still fires on a quiet stream instead
    of blocking in the poll loop with dirty entities unpublished.

    A final line without a newline is a write in flight: under follow the
    reader waits for the rest; without follow it is skipped with a warning
    (the next run's cursor has not passed it, so nothing is lost). A
    malformed COMPLETE line raises :class:`EventError` — a corrupt log must
    fail loud, not silently drop labeled data.
    """
    with open(path, "r", encoding="utf-8") as f:
        buf = ""
        idle_since = time.monotonic()
        while True:
            chunk = f.readline()
            if chunk:
                buf += chunk
                if not buf.endswith("\n"):
                    continue  # torn tail: wait for the rest of the line
                line, buf = buf.strip(), ""
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    raise EventError(
                        f"{path}: corrupt event line: {line[:120]!r}"
                    ) from None
                ev = OnlineEvent.from_dict(d)
                idle_since = time.monotonic()
                if ev.seq >= start_seq:
                    yield ev
                continue
            # EOF
            if not follow:
                if buf:
                    logger.warning(
                        "%s: unterminated final line (%d bytes) skipped — "
                        "a write in flight; the cursor has not passed it",
                        path, len(buf),
                    )
                return
            if stop is not None and stop():
                return
            if idle_yield_s > 0 and \
                    time.monotonic() - idle_since >= idle_yield_s:
                idle_since = time.monotonic()
                yield None  # idle tick: let the consumer's cadence fire
            time.sleep(poll_s)


class EventCursor:
    """Replay position, persisted as ``<dir>/online-cursor.json``.

    ``next_seq`` is the first UNPUBLISHED event: the trainer saves the
    cursor only after a delta publish succeeds, so a crash between refresh
    and publish replays those events — refreshes are idempotent re-solves
    over the window, so replay converges to the same coefficients.
    """

    FILENAME = "online-cursor.json"

    def __init__(self, out_dir: str):
        self.path = os.path.join(out_dir, self.FILENAME)
        os.makedirs(out_dir, exist_ok=True)

    def load(self) -> int:
        try:
            with open(self.path) as f:
                return int(json.load(f).get("next_seq", 0))
        except (OSError, ValueError):
            return 0

    def save(self, next_seq: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "next_seq": int(next_seq),
                "updated_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            }, f)
        os.replace(tmp, self.path)  # atomic: never a torn cursor


def resolve_event_features(
    event: OnlineEvent,
    index_maps: Mapping[str, object],
    shard_configs: Mapping[str, object],
    shards: Sequence[str],
    max_nnz: int,
) -> dict:
    """Event feature bags → fixed-width ELL rows, one per shard.

    The same resolution rules as the serving request parser
    (``RowScorer.parse_request``) and the reader: features resolve through
    the shard's index map, unindexed features DROP, the intercept column is
    prepended when the shard config says so, and a row over ``max_nnz``
    indexed features is refused (stable-shape contract — raise the knob,
    never truncate). Returns ``{shard: (idx[int32 K], val[float32 K])}``
    with ghost padding ``== len(index_map)``.
    """
    out = {}
    for shard in shards:
        imap = index_maps[shard]
        cfg = shard_configs[shard]
        dim = len(imap)
        idxs, vals = [], []
        icpt = imap.intercept_index if getattr(cfg, "add_intercept", False) \
            else None
        if icpt is not None and icpt >= 0:
            idxs.append(icpt)
            vals.append(1.0)
        for bag in cfg.feature_bags:
            feats = event.features.get(bag)
            if feats is None:
                continue
            for feat in feats:
                try:
                    i = imap.get_index(feat["name"], feat.get("term"))
                    v = float(feat["value"])
                except (TypeError, KeyError, ValueError) as e:
                    raise EventError(
                        f"bad feature entry in bag {bag!r}: {e}"
                    ) from None
                if i >= 0:  # unindexed features dropped, as the reader
                    idxs.append(i)
                    vals.append(v)
        if len(idxs) > max_nnz:
            raise EventError(
                f"event has {len(idxs)} indexed features in shard "
                f"{shard!r}; the online trainer caps rows at "
                f"max_event_nnz={max_nnz} (raise the knob, don't truncate)"
            )
        row_i = np.full(max_nnz, dim, np.int32)
        row_v = np.zeros(max_nnz, np.float32)
        row_i[: len(idxs)] = idxs
        row_v[: len(vals)] = vals
        out[shard] = (row_i, row_v)
    return out
