"""The streaming incremental trainer (docs/online.md).

Consume → dirty → micro-batch refresh → delta publish, as one loop:

1. **Consume.** Each event's feature bags resolve once per shard (same
   rules as the serving request parser); the row's OFFSET is composed at
   ingest from the frozen fixed-effect coordinates plus the other
   random-effect coordinates' CURRENT published coefficients — the online
   analog of coordinate descent's "offsets from the other coordinates"
   (an entity refreshed later sees the offsets that were live at ingest,
   exactly the one-sweep-stale semantics batch GAME has mid-sweep).
2. **Refresh.** Dirty entities (oldest pending event first) re-solve on
   their sliding windows as ONE ``build_random_effect_dataset`` micro-batch:
   the same bucketing/projection machinery as batch training, solved
   through the blessed chunk-ladder Newton kernels
   (``fit_bucket_in_chunks`` at a FIXED ladder chunk, so entity counts pad
   to a closed set of lane shapes and the retrace sentinel stays quiet
   across cycles). Each entity's solve is anchored to its previous
   posterior via :class:`PriorDistribution` (``incremental_weight`` folds
   into the precisions; 0 disables anchoring entirely, making a
   full-window refresh mathematically identical to a batch retrain on the
   same rows — the convergence-equivalence contract tests/test_online.py
   enforces).
3. **Publish.** The refresh becomes a :class:`ModelDelta` (full
   replacement sparse vectors per changed entity; columns with no support
   in the window keep their previous posterior unchanged) handed to the
   publisher — in-process ``RegistryPublisher`` or HTTP
   ``POST /admin/patch``. State, dirty marks, the journal, and the replay
   cursor advance ONLY after the publish returns: a failed publish leaves
   everything pending and the next cycle retries the same entities.

Failure contract (PR 8): a classified device loss mid-refresh clears the
executable caches and re-runs the refresh bit-identically (windows and
priors are untouched until publish), bounded by
``PHOTON_DEVICE_LOST_MAX_RECOVERIES``; the ``online.refresh`` and
``online.publish`` fault points let the chaos suite drive both paths
deterministically.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Iterable, Mapping, Optional, Sequence

import numpy as np

from photon_tpu.faults import fault_point
from photon_tpu.obs import instant, trace_span
from photon_tpu.obs.metrics import REGISTRY
from photon_tpu.online.delta import EntityPatch, ModelDelta, PatchJournal
from photon_tpu.online.events import (
    EventCursor,
    EventError,
    OnlineEvent,
    resolve_event_features,
)
from photon_tpu.online.state import EntityWindows, OnlineModelState

logger = logging.getLogger("photon_tpu.online")

_EVENTS_TOTAL = REGISTRY.counter(
    "online_events_total",
    "events consumed by the online incremental trainer",
)
_ENTITIES_REFRESHED = REGISTRY.counter(
    "online_entities_refreshed_total",
    "entities re-solved and published by the online trainer",
)
_DELTAS_PUBLISHED = REGISTRY.counter(
    "online_deltas_published_total",
    "model deltas published into the serving registry",
)
_FRESHNESS = REGISTRY.histogram(
    "online_freshness_seconds",
    "event->published-delta freshness per refreshed entity (oldest "
    "pending event to publish completion)",
)
_DIRTY_GAUGE = REGISTRY.gauge(
    "online_dirty_entities",
    "entities with unrefreshed events, per coordinate",
)


@dataclasses.dataclass(frozen=True)
class OnlineCoordinate:
    """One refreshable random-effect coordinate."""

    cid: str
    re_type: str          # entity id column, e.g. "userId"
    feature_shard: str


@dataclasses.dataclass(frozen=True)
class OnlineTrainerConfig:
    """Operational knobs (docs/online.md §knobs)."""

    window: int = 64              # sliding-window rows per entity
    max_event_nnz: int = 64       # fixed per-shard feature width per event
    refresh_batch: int = 4096     # dirty entities per refresh cycle (cap)
    chunk: int = 256              # blessed lane count (must be on the
                                  # PHOTON_RE_CHUNK_LADDER — stable shapes)
    cadence_s: float = 0.0        # 0 = refresh on batch-full / drain only
    incremental_weight: float = 1.0   # prior anchor strength (0 = none)
    reg_weight: float = 1.0       # per-refresh L2 weight
    max_iterations: int = 30
    tolerance: float = 1e-7
    dtype: str = "float32"        # solve precision for assembled windows

    def __post_init__(self):
        from photon_tpu.game.newton_re import chunk_ladder

        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.refresh_batch < 1:
            raise ValueError(
                f"refresh_batch must be >= 1, got {self.refresh_batch}")
        if self.incremental_weight < 0.0:
            raise ValueError(
                "incremental_weight must be >= 0, got "
                f"{self.incremental_weight}")
        if self.chunk not in chunk_ladder():
            raise ValueError(
                f"chunk={self.chunk} is not on the blessed chunk ladder "
                f"{chunk_ladder()} (PHOTON_RE_CHUNK_LADDER): off-ladder "
                "lane counts would compile a new XLA executable per "
                "refresh and trip the retrace sentinel"
            )


class RegistryPublisher:
    """In-process delta publisher: applies straight to a live
    ``ModelRegistry`` (the bench / embedded-trainer path)."""

    def __init__(self, registry):
        self.registry = registry

    def publish(self, delta: ModelDelta) -> dict:
        return self.registry.apply_delta(
            delta.raw_patches(), seq=delta.seq,
            event_horizon=delta.event_horizon,
        )


_PUBLISH_RETRIES = REGISTRY.counter(
    "online_publish_retries_total",
    "delta publish attempts retried on a transient connection error",
)


class HttpPublisher:
    """Cross-process delta publisher: ``POST /admin/patch`` against a live
    scoring server (docs/online.md §"Delta protocol").

    Transient connection failures (refused/reset/timeout — a serving
    replica restarting mid-publish) retry with bounded backoff using the
    supervisor's decorrelated-jitter :class:`RestartPolicy` math (``seed``
    pins the delay stream for tests); each retry bumps
    ``online_publish_retries_total``. An HTTP *response* never retries:
    the server got the delta, and a validation 4xx would fail identically
    forever — except a 503 shed, which is a "not now" the backoff exists
    for.

    Retry semantics are AT-LEAST-ONCE on the wire but exactly-once at the
    server: every POST carries ``X-Photon-Idempotency-Key`` (the delta's
    ``seq`` + content digest, :meth:`ModelDelta.idempotency_key`), so a
    timeout that fired AFTER the server applied the patch — reply lost in
    flight — makes the retry replay the first application's cached result
    (``"duplicate": true`` in the reply, ``serve_patch_duplicates_total``
    bumped) instead of re-applying. ``patch_seq``,
    ``patched_entities_total``, and the ``serving.delta_applied``
    journal/trace rows therefore count each logical delta once. For
    durable write-once fan-out with a per-seq exactly-once audit, use the
    delta log instead (``photon_tpu.replication`` — docs/serving.md
    §"Replication")."""

    def __init__(self, base_url: str, timeout_s: float = 30.0,
                 retries: int = 3, backoff_s: float = 0.2,
                 max_backoff_s: float = 2.0,
                 seed: Optional[int] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        # Lazy import at call time keeps this module import-light; the
        # policy itself is a frozen dataclass, safe to build per publisher.
        from photon_tpu.supervisor import RestartPolicy

        self._policy = RestartPolicy(
            max_restarts=self.retries,
            backoff_seconds=float(backoff_s),
            max_backoff_seconds=float(max_backoff_s),
            seed=seed,
        )

    def publish(self, delta: ModelDelta) -> dict:
        import json
        import urllib.error
        import urllib.request

        from photon_tpu.obs import current_trace_id, instant

        headers = {"Content-Type": "application/json"}
        # Cross-process trace join (docs/observability.md §"Fleet view"):
        # the publish span's trace id rides the request so the serving
        # process's /admin/patch spans land on the SAME id — the fleet
        # merger then shows event→refresh→publish→apply as one flow.
        tid = current_trace_id()
        if tid is not None:
            headers["X-Photon-Trace-Id"] = tid
        # One key for ALL attempts of this publish call: the server
        # dedupes a retry whose predecessor applied but whose reply was
        # lost (class docstring — the at-least-once double-count fix).
        headers["X-Photon-Idempotency-Key"] = delta.idempotency_key()
        data = json.dumps(delta.to_wire()).encode("utf-8")
        delays = self._policy.delays()
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(
                self.base_url + "/admin/patch", data=data,
                headers=headers, method="POST",
            )
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                if e.code == 503 and attempt < self.retries:
                    # A shed/draining replica: transient by contract
                    # (503 + Retry-After), worth the backoff.
                    last = e
                else:
                    # Surface the server's actionable validation message
                    # (e.g. the over-wide-patch guidance), not just
                    # "HTTP Error 400".
                    detail = ""
                    try:
                        detail = e.read().decode("utf-8", "replace")[:500]
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                    raise RuntimeError(
                        f"delta publish rejected by {self.base_url} "
                        f"(HTTP {e.code}): {detail or e.reason}"
                    ) from e
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError, OSError) as e:
                # Connection-level failure. A refused/reset connection
                # means the server never saw the delta; a TIMEOUT may
                # fire after the server applied it with the reply in
                # flight, so this retry is at-least-once (class doc):
                # idempotent for coefficients (full-replacement patches),
                # but patch_seq and the delta_applied rows can
                # double-count the re-post.
                last = e
            if attempt >= self.retries:
                break
            delay = next(delays)
            _PUBLISH_RETRIES.inc()
            instant("online.publish_retry", cat="online",
                    attempt=attempt + 1, delay_s=round(delay, 3),
                    error=f"{type(last).__name__}: {str(last)[:200]}")
            logger.warning(
                "delta publish to %s failed (%s: %s); retry %d/%d in "
                "%.2fs", self.base_url, type(last).__name__, last,
                attempt + 1, self.retries, delay,
            )
            time.sleep(delay)
        raise RuntimeError(
            f"delta publish to {self.base_url} failed after "
            f"{self.retries + 1} attempt(s): "
            f"{type(last).__name__}: {last}"
        ) from last


class OnlineTrainer:
    """Streaming per-entity delta trainer (module doc).

    ``publisher`` is anything with ``publish(ModelDelta) -> dict``; None
    runs the trainer "open-loop" (state + journal advance, nothing served —
    useful for shadow evaluation). ``on_bad_event`` receives
    (:class:`EventError`, event dict) per malformed event (default: warn
    and continue — one producer bug must not kill the stream).
    """

    def __init__(
        self,
        task,
        coordinates: Sequence[OnlineCoordinate],
        index_maps: Mapping[str, object],
        shard_configs: Mapping[str, object],
        config: OnlineTrainerConfig = OnlineTrainerConfig(),
        publisher=None,
        fixed_weights: Optional[Mapping[str, tuple]] = None,
        journal: Optional[PatchJournal] = None,
        cursor: Optional[EventCursor] = None,
        on_bad_event: Optional[Callable] = None,
    ):
        if not coordinates:
            raise ValueError("online trainer needs >= 1 random-effect "
                             "coordinate")
        self.task = task
        self.coordinates = {c.cid: c for c in coordinates}
        self.index_maps = dict(index_maps)
        self.shard_configs = dict(shard_configs)
        self.config = config
        self.publisher = publisher
        self.journal = journal
        self.cursor = cursor
        self.on_bad_event = on_bad_event
        # Fixed-effect coordinates stay FROZEN online; their host-side
        # extended weight vectors (ghost column == dim -> 0) compose each
        # event's offset at ingest.
        self._fixed_ext: dict = {}
        for cid, (shard, w) in (fixed_weights or {}).items():
            w = np.asarray(w, np.float64)
            self._fixed_ext[cid] = (shard, np.concatenate([w, [0.0]]))
        self.windows: dict = {
            cid: EntityWindows(config.window) for cid in self.coordinates
        }
        self.state: dict = {
            cid: OnlineModelState() for cid in self.coordinates
        }
        self._shards_used = sorted(
            {c.feature_shard for c in coordinates}
            | {shard for shard, _ in self._fixed_ext.values()}
        )
        self._problem = self._build_problem()
        # Shape classes already compiled by THIS trainer: the first solve
        # of a new (solver, S, P) class at the fixed chunk is a legitimate
        # one-time compile (declared expected to the retrace sentinel, like
        # serving warmup); any LATER trace of a seen class is a genuine
        # hot-path retrace the sentinel must keep warning about.
        self._compiled_shapes: set = set()
        self._delta_seq = 0
        self._consumed_seq = -1       # highest event seq ingested
        self._last_refresh_t = time.monotonic()
        self.totals = {
            "events": 0, "bad_events": 0, "cycles": 0, "deltas": 0,
            "entities_refreshed": 0, "device_loss_recoveries": 0,
        }

    # ------------------------------------------------------------- assembly

    @classmethod
    def from_game_model(
        cls,
        model,
        data_configs: Mapping[str, object],
        index_maps: Mapping[str, object],
        shard_configs: Mapping[str, object],
        config: OnlineTrainerConfig = OnlineTrainerConfig(),
        **kwargs,
    ) -> "OnlineTrainer":
        """Seed from a trained/loaded ``GameModel`` + its data configs:
        fixed coordinates freeze into offset composers, random-effect
        coordinates seed the posterior state each refresh anchors to."""
        from photon_tpu.estimators.config import (
            FixedEffectDataConfig,
            RandomEffectDataConfig,
        )
        from photon_tpu.game.coordinates import FixedEffectModel

        coords, fixed, task = [], {}, None
        for cid, dcfg in data_configs.items():
            m = model[cid]
            if isinstance(dcfg, FixedEffectDataConfig):
                if not isinstance(m, FixedEffectModel):
                    raise TypeError(
                        f"{cid!r}: fixed-effect config, {type(m)} model")
                task = m.model.task
                fixed[cid] = (
                    dcfg.feature_shard,
                    np.asarray(m.model.coefficients.means, np.float64),
                )
            elif isinstance(dcfg, RandomEffectDataConfig):
                task = m.task
                coords.append(OnlineCoordinate(
                    cid=cid, re_type=dcfg.re_type,
                    feature_shard=dcfg.feature_shard,
                ))
        trainer = cls(
            task=task, coordinates=coords, index_maps=index_maps,
            shard_configs=shard_configs, config=config,
            fixed_weights=fixed, **kwargs,
        )
        for c in coords:
            trainer.state[c.cid] = (
                OnlineModelState.from_random_effect_model(model[c.cid]))
        return trainer

    def _build_problem(self):
        from photon_tpu.functions.problem import (
            GLMOptimizationProblem,
            VarianceComputationType,
        )
        from photon_tpu.optim import (
            OptimizerConfig,
            OptimizerType,
            RegularizationContext,
            RegularizationType,
        )

        # LBFGS type + smooth L2 keeps every refresh inside the history-free
        # Newton gates (newton_re._smooth_ok); SIMPLE variances feed the
        # next refresh's prior precisions.
        return GLMOptimizationProblem(
            task=self.task,
            optimizer_type=OptimizerType.LBFGS,
            optimizer_config=OptimizerConfig(
                max_iterations=self.config.max_iterations,
                tolerance=self.config.tolerance,
            ),
            regularization=RegularizationContext(RegularizationType.L2),
            reg_weight=self.config.reg_weight,
            variance_type=VarianceComputationType.SIMPLE,
        )

    # -------------------------------------------------------------- consume

    def ingest(self, event: OnlineEvent) -> bool:
        """Resolve one event into window rows; returns False on a
        malformed event (reported via ``on_bad_event``)."""
        try:
            rows = resolve_event_features(
                event, self.index_maps, self.shard_configs,
                self._shards_used, self.config.max_event_nnz,
            )
        except EventError as e:
            self.totals["bad_events"] += 1
            if self.on_bad_event is not None:
                self.on_bad_event(e, event)
            else:
                logger.warning("bad event (seq %d) skipped: %s",
                               event.seq, e)
            return False
        fixed_total = 0.0
        for shard, w_ext in self._fixed_ext.values():
            idx, val = rows[shard]
            fixed_total += float(np.sum(w_ext[idx] * np.asarray(
                val, np.float64)))
        any_entity = False
        for cid, coord in self.coordinates.items():
            key = event.entities.get(coord.re_type)
            if key is None:
                continue
            any_entity = True
            idx, val = rows[coord.feature_shard]
            offset = event.offset + fixed_total
            # Other coordinates' published contributions at INGEST time
            # (one-sweep-stale offsets — module doc).
            for ocid, other in self.coordinates.items():
                if ocid == cid:
                    continue
                okey = event.entities.get(other.re_type)
                if okey is None:
                    continue
                oidx, oval = rows[other.feature_shard]
                offset += self.state[ocid].score_contribution(
                    okey, oidx, oval,
                    len(self.index_maps[other.feature_shard]),
                )
            self.windows[cid].add_row(
                key, idx, val, event.label, event.weight, offset,
                event.ts or time.time(), event.seq,
            )
        self.totals["events"] += 1
        _EVENTS_TOTAL.inc()
        if event.seq >= 0:
            self._consumed_seq = max(self._consumed_seq, event.seq)
        return any_entity

    # -------------------------------------------------------------- refresh

    def n_dirty(self) -> int:
        return sum(w.n_dirty for w in self.windows.values())

    def _should_refresh(self) -> bool:
        if self.n_dirty() == 0:
            return False
        if any(w.n_dirty >= self.config.refresh_batch
               for w in self.windows.values()):
            return True
        return (self.config.cadence_s > 0.0
                and time.monotonic() - self._last_refresh_t
                >= self.config.cadence_s)

    def refresh(self) -> Optional[dict]:
        """One refresh cycle: re-solve dirty entities of every coordinate,
        publish ONE delta covering them all. Returns a summary dict, or
        None when nothing was dirty."""
        plan = {}
        for cid, w in self.windows.items():
            dirty = w.peek_dirty(self.config.refresh_batch)
            if dirty:
                plan[cid] = dirty
        if not plan:
            return None
        # Horizon: the highest event seq this refresh can cover. Captured
        # BEFORE solving so events racing in mid-solve stay dirty (and the
        # cursor never advances past unpublished data).
        horizon = self._consumed_seq
        t0 = time.monotonic()
        with trace_span("online.refresh", cat="online",
                        coordinates=sorted(plan),
                        entities=sum(len(d) for d in plan.values())) as sp:
            solved = self._solve_plan_recovering(plan)
            patches = {
                cid: self._merge_patches(cid, by_key)
                for cid, by_key in solved.items()
            }
            delta = ModelDelta(
                seq=self._delta_seq,
                patches=patches,
                event_horizon=horizon,
                created_ts=time.time(),
            )
            published = self._publish(delta, plan, solved)
            sp.set(seq=delta.seq, published=bool(self.publisher))
        wall = time.monotonic() - t0
        n = delta.n_entities
        self._last_refresh_t = time.monotonic()
        self.totals["cycles"] += 1
        self.totals["deltas"] += 1
        self.totals["entities_refreshed"] += n
        for cid, w in self.windows.items():
            _DIRTY_GAUGE.set(w.n_dirty, coordinate=cid)
        return {
            "seq": delta.seq,
            "entities": n,
            "coordinates": sorted(plan),
            "seconds": round(wall, 4),
            "entities_per_sec": round(n / wall, 1) if wall > 0 else None,
            "freshness_s": published["freshness_s"],
            "device_loss_recoveries": published["recoveries"],
        }

    def _solve_plan_recovering(self, plan: Mapping[str, list]) -> dict:
        """Solve every coordinate's dirty micro-batch, absorbing up to
        ``PHOTON_DEVICE_LOST_MAX_RECOVERIES`` classified device losses by
        clearing the executable caches and re-running bit-identically
        (windows/priors are immutable until publish, so the retry solves
        the exact same problem).

        An ``oom``-classified failure takes the DEGRADATION ladder instead
        (docs/robustness.md §"Memory pressure"): ``refresh_batch`` halves
        — sticky, the config stays halved for the trainer's lifetime — and
        the PLAN is trimmed in place to the new cap, so this cycle
        publishes a smaller delta and the un-trimmed entities simply stay
        dirty for the next cycle (exactly the existing refresh-batch cap
        semantics; no state mutates until publish, so nothing tears).
        Bounded by ``PHOTON_OOM_MAX_DOWNSHIFTS``."""
        from photon_tpu.obs import retrace
        from photon_tpu.runtime import memory_guard as _mg
        from photon_tpu.runtime.backend_guard import (
            is_device_lost,
            max_inrun_recoveries,
        )
        from photon_tpu.supervisor import clear_executable_caches

        recoveries = 0
        downshifted = False
        while True:
            try:
                fault_point("online.refresh",
                            entities=sum(len(d) for d in plan.values()))
                if recoveries or downshifted:
                    with retrace.expected_compiles():
                        out = {cid: self._solve_coordinate(cid, dirty)
                               for cid, dirty in plan.items()}
                else:
                    out = {cid: self._solve_coordinate(cid, dirty)
                           for cid, dirty in plan.items()}
                self._recoveries_last = recoveries
                return out
            except KeyboardInterrupt:
                raise  # a user abort is never a retryable device loss
            except Exception as e:  # noqa: BLE001 - classified below
                if _mg.is_oom(e):
                    cur = self.config.refresh_batch
                    new = max(1, cur // 2)
                    if new >= cur:
                        # No cheaper rung: journal the classified
                        # exhaustion before escalating (re.solve contract).
                        _mg.journal_event(
                            "oom_exhausted", site="online.refresh",
                            cause="oom", plan=f"refresh_batch={cur}",
                            reason="refresh_batch already 1")
                        raise
                    if not _mg.downshifter("online.refresh").absorb(
                            e, before=f"refresh_batch={cur}",
                            after=f"refresh_batch={new}"):
                        raise  # absorb journaled the spent budget
                    # Sticky: every later cycle plans at the halved cap.
                    self.config = dataclasses.replace(
                        self.config, refresh_batch=new)
                    for cid in list(plan):
                        plan[cid] = plan[cid][:new]
                    downshifted = True
                    continue
                if not is_device_lost(e) or \
                        recoveries >= max_inrun_recoveries():
                    raise
                recoveries += 1
                self.totals["device_loss_recoveries"] += 1
                instant("recovery.online_refresh", cat="recovery",
                        attempt=recoveries,
                        error=f"{type(e).__name__}: {str(e)[:200]}")
                logger.warning(
                    "device loss mid-refresh (%s); clearing executable "
                    "caches and re-running (recovery %d)", e, recoveries,
                )
                clear_executable_caches("online refresh recovery")
                # Every executable is gone; repopulate from the AOT compile
                # store when one is active (docs/robustness.md §"Recovery
                # time") so the retry LOADS its fixed-ladder kernels instead
                # of recompiling each shape class from scratch (either way,
                # declared expected above).
                from photon_tpu.runtime.compile_store import (
                    prewarm_if_active,
                )

                prewarm_if_active(reason="online refresh recovery",
                                  logger_=logger)
                self._compiled_shapes.clear()

    def _solve_coordinate(self, cid: str, dirty: list) -> dict:
        """Re-solve one coordinate's dirty entities on their windows.
        Returns ``{key: (cols, means, variances, first_pending_ts)}`` —
        host numpy only (the D2H fetch inside is the device sync, so a
        device loss surfaces HERE, before any state mutation)."""
        import jax.numpy as jnp

        from photon_tpu.data.random_effect import (
            build_random_effect_dataset,
        )

        coord = self.coordinates[cid]
        w = self.windows[cid]
        keys, first_ts = [], {}
        rows_keys, rows_idx, rows_val = [], [], []
        rows_lab, rows_wt, rows_off = [], [], []
        for key, ts, _seq in dirty:
            rows = w.rows_for(key)
            if not rows:
                continue
            keys.append(key)
            first_ts[key] = ts
            for (idx, val, label, weight, offset, _ts, _s) in rows:
                rows_keys.append(key)
                rows_idx.append(idx)
                rows_val.append(val)
                rows_lab.append(label)
                rows_wt.append(weight)
                rows_off.append(offset)
        if not keys:
            return {}
        dt = np.dtype(self.config.dtype)
        dim = len(self.index_maps[coord.feature_shard])
        dataset = build_random_effect_dataset(
            coord.re_type,
            np.asarray(rows_keys, object),
            np.stack(rows_idx).astype(np.int32),
            np.stack(rows_val).astype(dt),
            np.asarray(rows_lab, dt),
            global_dim=dim,
            weights=np.asarray(rows_wt, dt),
            dtype=dt,
        )
        offsets_vec = jnp.asarray(np.asarray(rows_off, dt))
        out: dict = {}
        for b_i, bucket in enumerate(dataset.buckets):
            batches = bucket.local_batches(offsets_vec)
            w0, prior = self._bucket_warmstart(cid, dataset, bucket, dt)
            mask = jnp.ones((bucket.n_entities, bucket.local_dim),
                            batches.features.val.dtype)
            with trace_span("online.solve", cat="online", coordinate=cid,
                            bucket=b_i, entities=bucket.n_entities,
                            local_dim=bucket.local_dim) as sp:
                models, solver = self._solve_bucket(
                    batches, w0, mask, prior)
                # D2H fetch = the device sync (block_until_ready does not
                # synchronize on the tunnel backend).
                means = np.asarray(models.coefficients.means)
                variances = (
                    np.asarray(models.coefficients.variances)
                    if models.coefficients.variances is not None else None
                )
                sp.set(solver=solver)
            proj = np.asarray(bucket.proj)
            eids = np.asarray(bucket.entity_ids)
            for lane in range(bucket.n_entities):
                dense = int(eids[lane])
                if dense < 0:
                    continue
                key = dataset.entity_keys[dense]
                pv = proj[lane]
                valid = pv < dim
                cols = pv[valid].astype(np.int64)
                out[key] = (
                    cols,
                    means[lane][valid].astype(np.float64),
                    (variances[lane][valid].astype(np.float64)
                     if variances is not None else None),
                    first_ts[key],
                )
        return out

    def _bucket_warmstart(self, cid: str, dataset, bucket, dt):
        """(w0, prior) for one bucket: previous posterior projected into
        each lane's local subspace. Missing entities/columns get the
        N(0, 1) default posterior — the same fill as
        ``RandomEffectModel.project_posteriors_to``; ``incremental_weight
        == 0`` returns no prior at all (plain warm start)."""
        import jax.numpy as jnp

        from photon_tpu.functions.prior import PriorDistribution

        state = self.state[cid]
        proj = np.asarray(bucket.proj)
        eids = np.asarray(bucket.entity_ids)
        e, p = proj.shape
        means = np.zeros((e, p), np.float64)
        var = np.ones((e, p), np.float64)
        for lane in range(e):
            dense = int(eids[lane])
            if dense < 0:
                continue
            post = state.posterior_for(dataset.entity_keys[dense])
            if post is None:
                continue
            cols, m, v = post
            if len(cols) == 0:
                continue
            pv = proj[lane]
            pos = np.clip(np.searchsorted(cols, pv), 0, len(cols) - 1)
            hit = (cols[pos] == pv) & (pv < dataset.global_dim)
            means[lane][hit] = m[pos[hit]]
            if v is not None:
                var[lane][hit] = v[pos[hit]]
        w0 = jnp.asarray(means.astype(dt))
        if self.config.incremental_weight <= 0.0:
            return w0, None
        return w0, PriorDistribution.from_model(
            jnp.asarray(means.astype(dt)), jnp.asarray(var.astype(dt)),
            self.config.incremental_weight,
        )

    def _solve_bucket(self, batches, w0, mask, prior):
        """History-free solve at a FIXED blessed chunk size: primal Newton
        for small local dims, span-reduced dual for the few-rows-wide-
        subspace regime, vmapped L-BFGS as the unconditional fallback.
        Every dispatch pads the entity axis to ``config.chunk`` lanes
        (``fit_bucket_in_chunks``), so cycle after cycle compiles NOTHING
        new once each (S, P) class has been seen (tests assert the trace
        counters stay flat)."""
        from photon_tpu.game.newton_re import (
            DUAL_MAX_T,
            NEWTON_MAX_P,
            fit_bucket_in_chunks,
            fit_bucket_newton,
            fit_bucket_newton_dual,
            penalty_terms,
            u_max_for,
        )
        from photon_tpu.game.random_effect import _fit_bucket_jitted

        problem = self._problem
        e, s, _ = batches.features.idx.shape
        p = batches.features.dim
        solver = "vmapped_lbfgs"
        if p <= NEWTON_MAX_P:
            solver = "newton_primal"

            def fit_one(b, w, m, pr):
                return fit_bucket_newton(problem, b, w, m, pr)

            def record_sig(b, w, m, pr):
                return ("fit_bucket_newton", fit_bucket_newton,
                        (problem, b, w, m, pr))
        elif s < p and s <= DUAL_MAX_T:
            u_max = u_max_for(penalty_terms(problem, mask, prior)[3])
            if s + u_max <= DUAL_MAX_T:
                solver = "newton_dual"

                def fit_one(b, w, m, pr):
                    return fit_bucket_newton_dual(problem, b, w, m, pr,
                                                  u_max)

                def record_sig(b, w, m, pr):
                    return ("fit_bucket_newton_dual", fit_bucket_newton_dual,
                            (problem, b, w, m, pr, u_max))
        if solver == "vmapped_lbfgs":
            def fit_one(b, w, m, pr):
                return _fit_bucket_jitted(problem, b, w, m, None, pr)

            def record_sig(b, w, m, pr):
                return ("fit_bucket_vmapped", _fit_bucket_jitted,
                        (problem, b, w, m, None, pr))
        shape_key = (solver, s, p, self.config.chunk,
                     str(batches.features.val.dtype),
                     prior is not None)
        if shape_key not in self._compiled_shapes:
            from photon_tpu.obs import retrace
            from photon_tpu.runtime.compile_store import record_if_active

            self._compiled_shapes.add(shape_key)

            recorded = []

            def fit_recorded(b, w, m, pr):
                # First cycle of this shape class: the per-chunk args are
                # the exact padded avals the kernel compiles at — record
                # them so a device-loss recovery (or restarted trainer)
                # pre-warms the fixed ladder from the store. Once per
                # shape class: every chunk is padded to the SAME lanes, so
                # later chunks would only re-pickle the identical
                # signature into the dedup check.
                out = fit_one(b, w, m, pr)
                if not recorded:
                    recorded.append(True)
                    kernel, fn, args = record_sig(b, w, m, pr)
                    record_if_active(kernel, fn, args)
                return out

            with retrace.expected_compiles():
                models, _result = fit_bucket_in_chunks(
                    fit_recorded, self.config.chunk, batches, w0, mask,
                    prior)
        else:
            models, _result = fit_bucket_in_chunks(
                fit_one, self.config.chunk, batches, w0, mask, prior)
        return models, solver

    # -------------------------------------------------------------- publish

    def _merge_patches(self, cid: str, solved: Mapping[str, tuple]) -> dict:
        """Solve results → full replacement patches: columns with no
        support in the entity's window keep their previous posterior
        value (the prior is the only force on them, and its optimum IS the
        previous mean)."""
        state = self.state[cid]
        out = {}
        for key, (cols, means, variances, _ts) in solved.items():
            prev = state.posterior_for(key)
            if prev is not None and len(prev[0]):
                pcols, pmeans, _pv = prev
                keep = ~np.isin(pcols, cols)
                if keep.any():
                    cols = np.concatenate([cols, pcols[keep]])
                    means = np.concatenate([means, pmeans[keep]])
                    order = np.argsort(cols)
                    cols, means = cols[order], means[order]
            out[key] = EntityPatch(
                key=str(key), cols=cols.astype(np.int32),
                vals=means.astype(np.float32),
            )
        return out

    def _publish(self, delta: ModelDelta, plan: Mapping[str, list],
                 solved: Mapping[str, Mapping[str, tuple]]) -> dict:
        """Publish + commit: state, dirty marks, journal, cursor advance
        ONLY after the publisher returns. The commit order is the no-torn-
        delta contract's trainer half (the store half is the overlay
        swap): an exception anywhere in here leaves every window dirty and
        every posterior unrefreshed, so the next cycle re-solves and
        re-publishes the identical delta."""
        solved_keys = {cid: [k for k, _, _ in dirty]
                       for cid, dirty in plan.items()}
        publish_result = None
        from photon_tpu.obs import current_trace_id, new_trace_id, \
            trace_context

        # One trace id per publish, attached to this thread so the span
        # below AND the HttpPublisher's X-Photon-Trace-Id header carry it
        # — the serving side joins on the same id (fleet merge contract).
        with trace_context(current_trace_id() or new_trace_id()), \
                trace_span("online.publish", cat="online", seq=delta.seq,
                           entities=delta.n_entities) as sp:
            fault_point("online.publish", seq=delta.seq)
            if self.publisher is not None:
                publish_result = self.publisher.publish(delta)
            now = time.time()
            fresh = []
            for cid, dirty in plan.items():
                for key, ts, _seq in dirty:
                    if key in delta.patches.get(cid, {}):
                        fresh.append(max(0.0, now - ts))
            for f in fresh:
                _FRESHNESS.observe(f)
            sp.set(freshness_max_s=round(max(fresh), 4) if fresh else None)
        # -- commit (post-publish) ----------------------------------------
        for cid, by_key in delta.patches.items():
            state = self.state[cid]
            for key, patch in by_key.items():
                # Variances aligned to the (merged) patch columns: solved
                # columns take the fresh SIMPLE variances, carried-over
                # columns keep their previous posterior width — the anchor
                # for the NEXT refresh of this entity.
                state.update(key, patch.cols.astype(np.int64),
                             patch.vals.astype(np.float64),
                             _aligned_variances(
                                 patch, state.posterior_for(key),
                                 solved.get(cid, {}).get(key)))
            self.windows[cid].clear_dirty(solved_keys[cid],
                                          horizon=delta.event_horizon)
        _DELTAS_PUBLISHED.inc()
        for cid, by_key in delta.patches.items():
            _ENTITIES_REFRESHED.inc(len(by_key), coordinate=cid)
        if self.journal is not None:
            self.journal.record(delta, publish_result or {"local": True},
                                freshness_s=fresh)
        if self.cursor is not None:
            # The HORIZON, not the live consumed seq: events ingested while
            # this refresh solved are unpublished and must replay after a
            # restart.
            self.cursor.save(delta.event_horizon + 1)
        self._delta_seq += 1
        return {"freshness_s": fresh, "recoveries":
                getattr(self, "_recoveries_last", 0),
                "publish_result": publish_result}

    # ----------------------------------------------------------------- run

    def run(
        self,
        events: Iterable[OnlineEvent],
        max_cycles: Optional[int] = None,
        drain: bool = True,
    ) -> dict:
        """Consume the stream, refreshing on the configured cadence; a
        final drain refresh covers the tail. ``None`` items are IDLE TICKS
        (a followed-but-quiet stream — ``iter_events(idle_yield_s=...)``):
        nothing ingests, but the cadence check still runs so dirty
        entities never sit unpublished waiting for the next event.
        Returns a totals summary."""
        refresh_summaries = []
        for ev in events:
            if ev is not None:
                self.ingest(ev)
            if self._should_refresh():
                s = self.refresh()
                if s is not None:
                    refresh_summaries.append(s)
                if max_cycles is not None and \
                        self.totals["cycles"] >= max_cycles:
                    break
        if drain and (max_cycles is None
                      or self.totals["cycles"] < max_cycles):
            s = self.refresh()
            if s is not None:
                refresh_summaries.append(s)
        fresh = [f for s in refresh_summaries for f in s["freshness_s"]]
        fresh.sort()

        def q(p: float) -> Optional[float]:
            if not fresh:
                return None
            return fresh[min(len(fresh) - 1, int(p * len(fresh)))]

        return {
            **self.totals,
            "refreshes": refresh_summaries,
            "freshness_p50_s": q(0.50),
            "freshness_p95_s": q(0.95),
            "freshness_samples": len(fresh),
        }


def _aligned_variances(patch: EntityPatch, prev, solved) -> np.ndarray:
    """Posterior variances for a patch's merged column set: default 1,
    previous posterior where carried over, fresh solved variances where
    re-solved (solved wins on overlap — it saw the window's data)."""
    var = np.ones(len(patch.cols), np.float64)
    pcols = patch.cols.astype(np.int64)
    for src in (prev, solved):
        if src is None:
            continue
        scols, svar = np.asarray(src[0], np.int64), src[2]
        if svar is None or len(scols) == 0:
            continue
        pos = np.searchsorted(pcols, scols)
        ok = pos < len(pcols)
        ok[ok] &= pcols[pos[ok]] == scols[ok]
        var[pos[ok]] = np.asarray(svar, np.float64)[ok]
    return var
