"""Model deltas: changed-entity coefficient patches + the patch journal.

Publication is by DELTA, never snapshot: a refresh of 500 entities out of
10M ships 500 sparse coefficient vectors, applied atomically to the
serving ``CoefficientStore`` overlay (``apply_patches`` swaps one dict
reference — a scoring thread sees the whole delta or none of it) with the
device LRU hot-set invalidated only for the patched keys.

The wire format (``POST /admin/patch``, docs/online.md §"Delta protocol"):

    {"seq": 12, "event_horizon": 4096,
     "patches": {"perUser": {"u3": {"cols": [0, 7], "vals": [0.2, -1.1]}}}}

``cols`` are GLOBAL feature columns, ascending (the layout the scoring
kernel's binary search requires — validated at apply). ``seq`` is the
trainer's delta sequence; ``event_horizon`` the highest event seq the delta
covers, so the journal is a replayable record of WHICH data produced WHICH
published coefficients.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Mapping, Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class EntityPatch:
    """One entity's full replacement coefficient vector (sparse, global
    columns ascending)."""

    key: str
    cols: np.ndarray    # int32, ascending
    vals: np.ndarray    # float32

    def __post_init__(self):
        cols = np.asarray(self.cols, np.int32)
        vals = np.asarray(self.vals, np.float32)
        if cols.shape != vals.shape or cols.ndim != 1:
            raise ValueError(
                f"patch for {self.key!r}: cols/vals must be matching 1-D "
                f"arrays, got {cols.shape} vs {vals.shape}"
            )
        if len(cols) > 1 and np.any(np.diff(cols) < 0):
            order = np.argsort(cols)   # defensive: kernel needs sorted cols
            cols, vals = cols[order], vals[order]
        object.__setattr__(self, "cols", cols)
        object.__setattr__(self, "vals", vals)

    @property
    def nnz(self) -> int:
        return int(len(self.cols))


@dataclasses.dataclass(frozen=True)
class ModelDelta:
    """One published refresh: per-coordinate entity patches + provenance."""

    seq: int
    patches: Mapping[str, Mapping[str, EntityPatch]]  # cid -> key -> patch
    event_horizon: int = -1       # highest event seq covered
    created_ts: float = 0.0

    @property
    def n_entities(self) -> int:
        return sum(len(p) for p in self.patches.values())

    def coordinates(self) -> list:
        return sorted(self.patches)

    def to_wire(self) -> dict:
        """JSON wire form (``POST /admin/patch``)."""
        return {
            "seq": int(self.seq),
            "event_horizon": int(self.event_horizon),
            "patches": {
                cid: {
                    p.key: {
                        "cols": [int(c) for c in p.cols],
                        "vals": [float(v) for v in p.vals],
                    }
                    for p in by_key.values()
                }
                for cid, by_key in self.patches.items()
            },
        }

    def idempotency_key(self) -> str:
        """Identity for at-least-once publication dedupe
        (``X-Photon-Idempotency-Key`` on ``POST /admin/patch``).

        ``seq`` plus a digest of the canonical wire form — NOT the bare
        seq: a restarted trainer incarnation restarts ``_delta_seq`` at 0
        (in-memory by design, PR 16), so two different incarnations reuse
        low seqs for genuinely different deltas, and those must both
        apply. Content-addressing makes the key collide exactly when the
        payload is byte-identical — i.e. exactly when a retry of the SAME
        publish is in flight."""
        import hashlib

        digest = hashlib.sha256(
            json.dumps(self.to_wire(), sort_keys=True).encode()
        ).hexdigest()[:16]
        return f"{int(self.seq)}:{digest}"

    @classmethod
    def from_wire(cls, d: dict) -> "ModelDelta":
        if not isinstance(d, dict) or not isinstance(d.get("patches"), dict):
            raise ValueError('delta must be {"patches": {cid: {key: ...}}}')
        patches: dict = {}
        for cid, by_key in d["patches"].items():
            if not isinstance(by_key, dict):
                raise ValueError(f"coordinate {cid!r}: patches must be a map")
            out = {}
            for key, p in by_key.items():
                try:
                    out[key] = EntityPatch(
                        key=str(key),
                        cols=np.asarray(p["cols"], np.int32),
                        vals=np.asarray(p["vals"], np.float32),
                    )
                except (TypeError, KeyError, ValueError) as e:
                    raise ValueError(
                        f"coordinate {cid!r} entity {key!r}: bad patch: {e}"
                    ) from None
            patches[cid] = out
        return cls(
            seq=int(d.get("seq", -1)),
            patches=patches,
            event_horizon=int(d.get("event_horizon", -1)),
            created_ts=float(d.get("created_ts") or 0.0),
        )

    def raw_patches(self) -> dict:
        """``{cid: {key: (cols, vals)}}`` — the shape the serving layer's
        ``ModelRegistry.apply_delta`` consumes (serving never imports the
        online package)."""
        return {
            cid: {p.key: (p.cols, p.vals) for p in by_key.values()}
            for cid, by_key in self.patches.items()
        }


class PatchJournal:
    """Append-only JSONL record of every published delta.

    Lives at ``<output-dir>/patch-journal.jsonl`` under the same
    whole-line O_APPEND contract as the recovery journal: one publish, one
    line, readable while being written. The journal is the durable side of
    the overlay (the serving store's patch overlay is process state): a
    replacement server can be caught up by replaying the journal tail, and
    a chaos drill asserts the journal never records a delta the store does
    not fully hold.
    """

    FILENAME = "patch-journal.jsonl"

    def __init__(self, out_dir: str):
        self.path = os.path.join(out_dir, self.FILENAME)
        os.makedirs(out_dir, exist_ok=True)
        self._fd = os.open(self.path,
                           os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)

    def record(self, delta: ModelDelta, published: dict,
               freshness_s: Optional[Sequence[float]] = None) -> dict:
        fresh = list(freshness_s or ())
        row = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "seq": int(delta.seq),
            "event_horizon": int(delta.event_horizon),
            "coordinates": delta.coordinates(),
            "entities": delta.n_entities,
            "published": published,
            "freshness_max_s": round(max(fresh), 4) if fresh else None,
        }
        os.write(self._fd, (json.dumps(row) + "\n").encode("utf-8"))
        return row

    def read_all(self) -> list:
        try:
            with open(self.path) as f:
                return [json.loads(x) for x in f if x.strip()]
        except OSError:
            return []

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "PatchJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
