"""Trainer-side state: per-entity sliding windows, dirty set, posteriors.

Three host-side structures, all keyed by entity:

* :class:`EntityWindows` — the last ``window`` observation rows per entity
  (a bounded deque of fixed-width ELL rows). The window IS the refresh's
  training data: each refresh re-solves the entity's GLM on its window,
  anchored to the previous posterior, so the solve stays a bounded-size
  batched Newton problem no matter how long the stream runs.
* the **dirty set** (inside :class:`EntityWindows`) — entities with events
  since their last published refresh, ordered by the FIRST pending event's
  timestamp. Refresh cycles drain oldest-first, so the freshness histogram
  measures the true worst-wait, not a lucky recent arrival.
* :class:`OnlineModelState` — the trainer's per-entity posterior (sparse
  global cols → means + variances). Seeded from the base model's export;
  updated only AFTER a delta publish succeeds, so the prior each refresh
  anchors to is exactly what serving is scoring with.
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Optional, Sequence

import numpy as np


class EntityWindows:
    """Sliding windows + dirty-set bookkeeping for ONE random-effect
    coordinate. Thread-safe: the consume loop appends while a refresh
    drains (the trainer serializes refreshes, but ingest may be a
    different thread)."""

    def __init__(self, window: int):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self._rows: dict = {}            # key -> deque of row tuples
        # key -> (first_pending_ts, first_pending_seq); insertion order is
        # NOT the refresh order — pop_dirty sorts by first pending ts.
        self._dirty: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.rows_total = 0

    def add_row(
        self, key: str, idx: np.ndarray, val: np.ndarray,
        label: float, weight: float, offset: float, ts: float, seq: int,
    ) -> None:
        """Append one observation row; marks the entity dirty."""
        with self._lock:
            dq = self._rows.get(key)
            if dq is None:
                dq = self._rows[key] = deque(maxlen=self.window)
            dq.append((idx, val, float(label), float(weight),
                       float(offset), float(ts), int(seq)))
            self.rows_total += 1
            if key not in self._dirty:
                self._dirty[key] = (float(ts), int(seq))

    @property
    def n_dirty(self) -> int:
        with self._lock:
            return len(self._dirty)

    @property
    def n_entities(self) -> int:
        with self._lock:
            return len(self._rows)

    def peek_dirty(self, max_n: int) -> list:
        """Up to ``max_n`` dirty keys, oldest first-pending-event first.
        Does NOT clear dirtiness — the trainer clears only after the
        refresh's delta publishes (``clear_dirty``), so a failed publish
        retries the same entities next cycle."""
        with self._lock:
            ordered = sorted(self._dirty.items(), key=lambda kv: kv[1])
            return [(k, ts, seq) for k, (ts, seq) in ordered[:max_n]]

    def clear_dirty(self, keys: Sequence[str],
                    horizon: Optional[int] = None) -> None:
        """Un-mark ``keys`` up to event seq ``horizon``: a key whose window
        holds an event NEWER than the just-published horizon stays dirty,
        re-stamped with that event's (ts, seq) — an ingest thread racing a
        refresh can never lose an event's refresh."""
        with self._lock:
            for k in keys:
                if horizon is not None:
                    dq = self._rows.get(k)
                    pending = next(
                        (r for r in (dq or ()) if r[6] > horizon), None)
                    if pending is not None:
                        self._dirty[k] = (pending[5], pending[6])
                        continue
                self._dirty.pop(k, None)

    def rows_for(self, key: str) -> list:
        """Current window rows for one entity (snapshot list)."""
        with self._lock:
            dq = self._rows.get(key)
            return list(dq) if dq else []

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entities": len(self._rows),
                "dirty": len(self._dirty),
                "rows_total": self.rows_total,
                "window": self.window,
            }


class OnlineModelState:
    """Per-entity posterior (cols → means, variances) for one coordinate.

    This is the trainer's mirror of what serving holds after every
    published delta: means are the serving coefficients, variances the
    posterior widths the NEXT refresh's :class:`PriorDistribution` derives
    its precisions from (missing variances default to 1 — the same
    unit-variance default as ``PriorDistribution.from_model``).
    """

    def __init__(self):
        self._by_key: dict = {}   # key -> (cols i64, means f64, vars f64|None)

    @classmethod
    def from_random_effect_model(cls, model) -> "OnlineModelState":
        """Seed from a loaded/trained ``RandomEffectModel`` via its sparse
        per-entity export (one host pass, same gather as the coefficient
        store build)."""
        st = cls()
        for key in model.entity_keys:
            gi, gv, vv = model.export_for(key)
            st._by_key[str(key)] = (
                np.asarray(gi, np.int64),
                np.asarray(gv, np.float64),
                None if vv is None else np.asarray(vv, np.float64),
            )
        return st

    @property
    def n_entities(self) -> int:
        return len(self._by_key)

    def posterior_for(
        self, key: str
    ) -> Optional[tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
        return self._by_key.get(key)

    def update(self, key: str, cols: np.ndarray, means: np.ndarray,
               variances: Optional[np.ndarray]) -> None:
        self._by_key[str(key)] = (
            np.asarray(cols, np.int64),
            np.asarray(means, np.float64),
            None if variances is None else np.asarray(variances, np.float64),
        )

    def score_contribution(self, key: str, idx: np.ndarray,
                           val: np.ndarray, dim: int) -> float:
        """This entity's additive score for one ELL row (host dot) — used
        when composing another coordinate's offsets. Unseen entities score
        0 (the zero-model fallback, as everywhere else)."""
        post = self._by_key.get(key)
        if post is None:
            return 0.0
        cols, means, _ = post
        valid = idx < dim
        if not valid.any():
            return 0.0
        pos = np.searchsorted(cols, idx[valid])
        pos = np.clip(pos, 0, max(len(cols) - 1, 0))
        hit = (len(cols) > 0) & (cols[pos] == idx[valid]) \
            if len(cols) else np.zeros(valid.sum(), bool)
        if not np.any(hit):
            return 0.0
        return float(np.sum(means[pos[hit]] * val[valid][hit]))
