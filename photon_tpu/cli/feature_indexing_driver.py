"""Feature indexing driver: build partitioned mmap index stores from data.

Parity: reference ⟦photon-client/.../index/FeatureIndexingDriver.scala⟧
(SURVEY.md §2.3): scan the dataset once per feature shard, assign every
``(name, term)`` pair a dense column id, and persist a partitioned off-heap
store (reference: PalDB; here: the mmap store of ``index/index_map.py``) that
training/scoring jobs load in O(1).
"""
from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from photon_tpu.cli.params import parse_feature_shard
from photon_tpu.index.index_map import build_mmap_index
from photon_tpu.io.data_reader import build_index_from_avro
from photon_tpu.utils import PhotonLogger, Timed


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="feature-indexing-driver",
        description="Build per-shard feature index stores from Avro data.",
    )
    p.add_argument("--data", nargs="+", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard", action="append", default=None,
                   metavar="SHARD[:BAG+BAG][:no-intercept]",
                   help="shard spec (repeatable); default 'global:features'")
    p.add_argument("--num-partitions", type=int, default=1,
                   help="hash partitions per store (reference PalDB partitions)")
    from photon_tpu.cli.params import (
        add_backend_policy_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_backend_policy_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import (
        enable_backend_guard,
        enable_telemetry,
        enable_trace,
        finish_telemetry,
        finish_trace,
    )

    # Indexing is host-side work, but the native block decoder's jax
    # imports can still initialize a backend; the same fail-fast gate (and
    # --backend-policy cpu-only for pure-host runs) applies.
    enable_backend_guard(args)
    enable_telemetry(args, role="indexing")
    enable_trace(args.trace_out)
    try:
        return _run(args)
    finally:
        finish_trace(args.trace_out)
        finish_telemetry(args)


def _run(args) -> dict:
    os.makedirs(args.output_dir, exist_ok=True)
    with PhotonLogger(args.output_dir) as logger:
        sizes = {}
        for spec in args.feature_shard or ["global:features"]:
            s = parse_feature_shard(spec)
            with Timed(f"index shard {s.shard}", logger):
                imap = build_index_from_avro(
                    args.data,
                    feature_bags=s.feature_bags,
                    add_intercept=s.add_intercept,
                )
                build_mmap_index(
                    imap,
                    os.path.join(args.output_dir, s.shard),
                    num_partitions=args.num_partitions,
                )
            sizes[s.shard] = len(imap)
            logger.info("shard %s: %d features", s.shard, len(imap))
        return {"features_per_shard": sizes}


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
