"""CLI drivers — reference ⟦photon-client/.../cli⟧ (SURVEY.md §1 L7):
``game_training_driver``, ``game_scoring_driver``, ``feature_indexing_driver``.
Each exposes ``run(argv) -> summary dict`` for programmatic use and ``main()``
as the console entry point."""
