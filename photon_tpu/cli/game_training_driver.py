"""GAME training driver: the end-to-end CLI training pipeline.

Parity: reference ⟦photon-client/.../cli/game/training/GameTrainingDriver.scala⟧
(SURVEY.md §3.1): parse params → read Avro training (+validation) data through
feature index maps → optional normalization from feature statistics → data
sanity checks → GameEstimator.fit over the optimization-config sweep → select
best by the primary evaluator → save model(s) + index maps + metrics.

TPU-first: no spark-submit — a plain console entry point; the device mesh
replaces the executor fleet (``--devices`` chooses how many chips the data
axis spans). Index maps are saved next to the model so the scoring driver is
self-contained.

Usage example:

    python -m photon_tpu.cli.game_training_driver \
      --train-data data/train --validation-data data/val \
      --output-dir out --task LOGISTIC_REGRESSION \
      --feature-shard global:features \
      --coordinate "fixed:type=fixed,shard=global,reg=L2,reg_weights=0.1|1|10" \
      --coordinate "perUser:type=random,re_type=userId,shard=global,reg=L2,reg_weights=1" \
      --evaluators AUC LOGISTIC_LOSS --sweeps 2 --output-mode BEST
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
from contextlib import contextmanager
from typing import Optional, Sequence

import numpy as np

from photon_tpu.cli.params import (
    configs_from_specs,
    parse_coordinates,
    parse_feature_shard,
)
from photon_tpu.data.normalization import NormalizationType
from photon_tpu.data.validators import DataValidationType, sanity_check_data
from photon_tpu.estimators import (
    GameEstimator,
    RandomEffectDataConfig,
    select_best,
)
from photon_tpu.evaluation import EvaluationSuite
from photon_tpu.index.index_map import MmapIndexMap, build_mmap_index
from photon_tpu.io.data_reader import (
    AvroDataReader,
    FeatureShardConfig,
    build_index_from_avro,
)
from photon_tpu.io.model_io import save_game_model
from photon_tpu.types import TaskType
from photon_tpu.utils import PhotonLogger, Timed, write_metrics_jsonl


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-training-driver",
        description="Train a GAME (GLMix) model on TPU.",
    )
    p.add_argument("--train-data", nargs="+", required=True,
                   help="Avro files/dirs/globs with training data")
    p.add_argument("--validation-data", nargs="+", default=None)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", required=True,
                   choices=[t.name for t in TaskType])
    p.add_argument("--feature-shard", action="append", default=None,
                   metavar="SHARD[:BAG+BAG][:no-intercept]",
                   help="feature shard spec (repeatable); default 'global:features'")
    p.add_argument("--coordinate", action="append", required=True,
                   metavar="CID:K=V,...",
                   help="coordinate spec mini-DSL (repeatable); see cli/params.py")
    p.add_argument("--update-sequence", default=None,
                   help="comma-separated coordinate order (default: flag order)")
    p.add_argument("--sweeps", type=int, default=1,
                   help="coordinate-descent sweeps (reference coordinateDescentIterations)")
    p.add_argument("--evaluators", nargs="+", default=None,
                   help="evaluator specs; first is primary (AUC, RMSE, AUC:col, PRECISION@k:col)")
    p.add_argument("--normalization", default="NONE",
                   choices=[n.name for n in NormalizationType])
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.name for v in DataValidationType])
    p.add_argument("--output-mode", default="BEST", choices=["BEST", "ALL"],
                   help="save only the selected model or every swept config")
    p.add_argument("--model-input-dir", default=None,
                   help="warm-start GAME model directory (reference modelInputDirectory)")
    p.add_argument("--tuning", default=None, choices=["gp", "random"],
                   help="auto-tune per-coordinate reg weights instead of grid sweep")
    p.add_argument("--tuning-iterations", type=int, default=10)
    p.add_argument("--tuning-range", action="append", default=None,
                   metavar="CID:MIN:MAX",
                   help="reg-weight search range per coordinate (repeatable; log scale)")
    p.add_argument("--index-dir", default=None,
                   help="prebuilt per-shard mmap index maps (else built from training data)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable step-level checkpointing; a restarted run with "
                        "the same args auto-resumes from the newest snapshot")
    p.add_argument("--devices", type=int, default=0,
                   help="data-parallel mesh size; 0 = all visible devices, 1 = no mesh")
    p.add_argument("--mesh", default=None, metavar="data=4,model=2",
                   help="explicit 2D mesh axes; a 'model' axis shards fixed-effect "
                        "coefficients/optimizer state over it (overrides --devices)")
    p.add_argument("--offset-column", default="offset")
    p.add_argument("--weight-column", default="weight")
    p.add_argument("--response-column", default="response")
    p.add_argument("--uid-column", default="uid")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of the run to this "
                        "directory (viewable in TensorBoard / Perfetto; "
                        "reference parity: Timed/PhotonLogger sections -> "
                        "on-device profiler, SURVEY.md §5.1)")
    p.add_argument("--debug-nans", action="store_true",
                   help="enable jax_debug_nans: any NaN produced on device "
                        "raises at the op that made it instead of "
                        "propagating (SURVEY.md §5.2 numeric guards; slows "
                        "training — debugging aid only)")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"],
                   help="training precision. float64 enables jax x64 and "
                        "matches the reference's double-precision (Breeze) "
                        "convergence semantics; float32 is the TPU-fast "
                        "default with a convergence floor around 1e-6 "
                        "relative (documented in tests/test_precision.py)")
    p.add_argument("--max-restarts", type=int, default=0,
                   help="restart the pipeline up to N times on retryable "
                        "failures (device/runtime/IO errors); pair with "
                        "--checkpoint-dir so each attempt resumes past "
                        "completed coordinate steps instead of recomputing "
                        "(the reference's Spark task-retry/lineage recovery, "
                        "SURVEY.md §5.3, as checkpoint-restart)")
    p.add_argument("--restart-backoff", type=float, default=5.0,
                   help="seconds before the first restart (doubles each time)")
    p.add_argument("--heartbeat-dir", default=None,
                   help="shared directory for multi-host liveness beacons; "
                        "each process writes a heartbeat file and restart "
                        "attempts fail fast with the dead-host list instead "
                        "of hanging in a collective (SURVEY.md §5.3)")
    p.add_argument("--ingest-workers", type=int, default=0,
                   help="decode input files with this many worker processes "
                        "(native block decoder per worker, file-sharded; "
                        "the reference's per-executor-core split decode, "
                        "SURVEY.md §2.3/§2.6); 0/1 = in-process")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="chunks the ingest pipeline decodes AHEAD on a "
                        "background thread (io/prefetch.py): block decode of "
                        "chunk N+1 overlaps downstream work on chunk N. "
                        "Default PHOTON_PREFETCH_DEPTH (2); 0 = sequential "
                        "decode (the pre-pipeline behavior)")
    p.add_argument("--sweep-cache-mb", type=float, default=None,
                   help="device-resident sweep cache budget in MB "
                        "(data/device_cache.py): multi-sweep training pins "
                        "host-resident coordinate data on device after "
                        "sweep 0 instead of re-uploading per sweep. Default "
                        "PHOTON_SWEEP_CACHE_MB (2048); 0 disables")
    p.add_argument("--bf16-feed", action="store_true",
                   help="transfer feature VALUES host->device as bfloat16 "
                        "(half the hot-path transfer bytes); solves "
                        "accumulate in float32 via dtype promotion. Opt-in: "
                        "continuous features round to 8 mantissa bits "
                        "(tolerance documented in tests/test_prefetch.py). "
                        "Incompatible with --dtype float64")
    p.add_argument("--feature-summary", action="store_true",
                   help="write per-feature summary statistics (mean/var/min/"
                        "max/nnz) for every shard to <output-dir>/summary/"
                        "<shard>.avro (reference FeatureSummarizationResultAvro "
                        "output, SURVEY.md §3.1 feature-summarization stage)")
    from photon_tpu.cli.params import (
        add_backend_policy_flag,
        add_compilation_cache_flag,
        add_compile_store_flag,
        add_distributed_flags,
        add_fault_plan_flag,
        add_re_routing_flags,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_backend_policy_flag(p)
    add_distributed_flags(p)
    add_compilation_cache_flag(p)
    add_compile_store_flag(p)
    add_fault_plan_flag(p)
    add_re_routing_flags(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


@contextmanager
def _checkpointing(directory: Optional[str]):
    """Optional CheckpointManager lifecycle: close on success; on failure
    drain without masking the original error (a leaked writer thread would
    race a retrying supervisor's fresh manager on the same directory)."""
    if not directory:
        yield None
        return
    from photon_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(directory)
    try:
        yield mgr
    except BaseException:
        try:
            mgr.close()
        except Exception:
            pass
        raise
    else:
        mgr.close()


def _load_or_build_indexes(args, shard_specs, logger):
    shard_cfgs = {
        s.shard: FeatureShardConfig(
            feature_bags=s.feature_bags, add_intercept=s.add_intercept
        )
        for s in shard_specs
    }
    index_maps = {}
    if args.index_dir:
        for shard in shard_cfgs:
            index_maps[shard] = MmapIndexMap(os.path.join(args.index_dir, shard))
            logger.info("index[%s]: loaded %d features (mmap)",
                        shard, len(index_maps[shard]))
    else:
        for shard, cfg in shard_cfgs.items():
            index_maps[shard] = build_index_from_avro(
                args.train_data,
                feature_bags=cfg.feature_bags,
                add_intercept=cfg.add_intercept,
            )
            logger.info("index[%s]: built %d features from training data",
                        shard, len(index_maps[shard]))
    return shard_cfgs, index_maps


def run(argv: Optional[Sequence[str]] = None) -> dict:
    """Run training; returns a result summary dict (also written to disk)."""
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import (
        enable_backend_guard,
        enable_compilation_cache,
        enable_compile_store,
        enable_fault_plan,
        enable_re_routing,
        enable_telemetry,
        enable_trace,
    )

    # Backend policy FIRST — the fail-fast probe (hard
    # PHOTON_BACKEND_INIT_TIMEOUT_S deadline) must gate the process before
    # anything can initialize a backend in-process and wedge.
    enable_backend_guard(args)
    enable_compilation_cache(args.compilation_cache_dir)
    # AOT compile store (after the cache flag so an explicit
    # --compilation-cache-dir stays the artifact layer): records every
    # blessed-kernel compile and pre-warms restarts/recoveries from it
    # (docs/robustness.md §"Recovery time"). On by default for every run
    # that can RESTART (supervised restarts, checkpoint resume) — the only
    # flows that re-enter compiled state — and opt-in via --compile-store
    # for one-shot runs.
    if args.compile_store or args.checkpoint_dir or args.max_restarts > 0:
        enable_compile_store(args, output_dir=args.output_dir)
    enable_fault_plan(args.fault_plan)
    enable_re_routing(args, output_dir=args.output_dir)
    # Fleet role + trace-shard placement BEFORE the collector installs:
    # the anchor event is stamped at install (docs/observability.md
    # §"Fleet view").
    enable_telemetry(args, role="training")
    enable_trace(args.trace_out)
    # Join the multi-host runtime first (no-op single-process) so
    # jax.devices() below sees the whole pod slice (SURVEY.md §5.8).
    # Bring-up failure is never silent: classified + journaled, and
    # --distributed-policy decides exit-2 vs degrade-to-single-host
    # (docs/scaling.md §"Multi-host mesh").
    from photon_tpu.parallel.distributed import initialize_distributed
    from photon_tpu.supervisor import RecoveryJournal

    os.makedirs(args.output_dir, exist_ok=True)
    initialize_distributed(
        policy=args.distributed_policy,
        journal=RecoveryJournal(
            os.path.join(args.output_dir, "recovery.jsonl")),
    )
    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    if args.debug_nans:
        import jax

        jax.config.update("jax_debug_nans", True)
    task = TaskType[args.task]
    os.makedirs(args.output_dir, exist_ok=True)
    profiling = False
    if args.profile_dir:
        import jax.profiler

        os.makedirs(args.profile_dir, exist_ok=True)
        jax.profiler.start_trace(args.profile_dir)
        profiling = True

    from photon_tpu.supervisor import Heartbeat, RestartPolicy, RunSupervisor

    heartbeat = None
    # SLO rules (docs/observability.md §SLO) ride the beat loop when a
    # config is provided: judged against the global registry snapshot
    # from the daemon thread, at most once a minute, surviving a wedged
    # main thread. The heartbeat IS the training driver's evaluation
    # point, so a config without a heartbeat dir must warn, not go
    # silent — silence is indistinguishable from "all SLOs passing".
    slo_watchdog = None
    slo_path = os.environ.get("PHOTON_SLO_CONFIG")
    if slo_path and not args.heartbeat_dir:
        import logging

        logging.getLogger("photon_tpu").warning(
            "PHOTON_SLO_CONFIG=%s is set but --heartbeat-dir is not: the "
            "training driver judges SLOs on the heartbeat loop, so this "
            "run will evaluate none of them", slo_path)
    if args.heartbeat_dir:
        if slo_path:
            from photon_tpu.obs.analysis.slo import SloConfig, SloWatchdog

            slo_watchdog = SloWatchdog(
                SloConfig.from_file(slo_path), min_interval_s=60.0)
        # Short interval: a retry must be able to tell "peer died with me"
        # from "peer is fine", so the staleness window (3x interval) has to
        # fit inside a restart backoff, not dwarf it. Every beat also
        # refreshes host_beacon_age_seconds{host=...} for the whole pod, so
        # the fleet view shows a dead host as a climbing gauge without
        # anyone reading beacon files (docs/observability.md §Fleet view).
        import jax

        heartbeat = Heartbeat(
            args.heartbeat_dir, interval_seconds=2.0,
            slo_watchdog=slo_watchdog,
            peer_gauges=range(jax.process_count()),
        ).start()

    def attempt(i: int) -> dict:
        if i > 0 and heartbeat is not None:
            import time as _time

            import jax

            # Let a freshly-dead peer's last beat age past the staleness
            # window before judging: the check runs backoff seconds after
            # our failure, so top up to 3x the beat interval if needed.
            settle = max(
                0.0, 3.0 * heartbeat.interval_seconds - args.restart_backoff
            )
            if settle:
                _time.sleep(settle)
            report = heartbeat.check_peers(range(jax.process_count()))
            if not report.healthy:
                raise RestartsUselessError(
                    f"peer hosts dead={report.dead} missing={report.missing}; "
                    "restart the job (checkpoint resume will fast-forward)"
                )
        watchdog = None
        if heartbeat is not None:
            import jax

            # Attempt-epoch barrier: a host may only (re-)enter the solve
            # once EVERY peer advertises the same attempt index. A lone
            # retrier would otherwise issue collectives that mismatch a peer
            # still blocked in the previous attempt's psum — all hosts then
            # hang with perfectly fresh heartbeats, invisible to both the
            # dead-peer check above and the liveness watchdog below.
            heartbeat.set_epoch(i)
            if i > 0 and jax.process_count() > 1:
                # (attempt 0 needs no barrier: the jax.distributed runtime
                # bring-up already synchronized process start.)
                laggards = heartbeat.wait_for_epoch(
                    range(jax.process_count()), i,
                    timeout_seconds=max(30.0, 3 * args.restart_backoff),
                )
                if laggards:
                    raise RestartsUselessError(
                        f"peer hosts {laggards} never reached attempt epoch "
                        f"{i} (wedged in a previous attempt's collective?); "
                        "restart the job (checkpoint resume will "
                        "fast-forward)"
                    )
            if jax.process_count() > 1:
                # LIVE detection (round-3 scope note closed): a psum whose
                # peer died blocks the main thread in C++ forever, so the
                # between-attempts check above can never run while an attempt
                # is wedged. Armed ONLY around the attempt body: between
                # attempts the graceful check_peers path (and the retry
                # loop's diagnostics) stay reachable. The watchdog aborts
                # from a daemon thread (exit 43) and hands recovery to the
                # scheduler restart + checkpoint resume.
                import logging

                watchdog = heartbeat.watchdog(
                    range(jax.process_count()),
                    logger=logging.getLogger("photon_tpu.supervisor"),
                ).start()
        try:
            return _run_inner(args, task)
        finally:
            if watchdog is not None:
                watchdog.stop()

    try:
        if args.max_restarts > 0:
            import logging

            # RunSupervisor (docs/robustness.md §recovery journal): same
            # RestartPolicy/backoff contract as run_with_recovery, plus
            # classified causes, run_restarts_total{cause=...}, recovery.*
            # trace events, and an append-only JSONL journal next to the
            # model — and under --backend-policy failover, a backend-level
            # failure re-probes between attempts and re-enters on CPU
            # instead of burning the whole budget on a wedged grant.
            supervisor = RunSupervisor(
                RestartPolicy(
                    max_restarts=args.max_restarts,
                    backoff_seconds=args.restart_backoff,
                ),
                journal=os.path.join(args.output_dir, "recovery.jsonl"),
                logger=logging.getLogger("photon_tpu.supervisor"),
                failover_policy=args.backend_policy,
            )
            return supervisor.run(attempt)
        return attempt(0)
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        if profiling:
            import jax.profiler

            jax.profiler.stop_trace()
        from photon_tpu.cli.params import finish_telemetry, finish_trace

        finish_trace(args.trace_out)
        finish_telemetry(args)


class RestartsUselessError(Exception):
    """A peer host is gone: in-process retry cannot succeed, so this escapes
    the retry loop (it is not a retryable type) and fails the job fast; the
    outer scheduler restarts all hosts and checkpoint resume takes over."""


def _run_inner(args, task) -> dict:
    with PhotonLogger(args.output_dir) as logger:
        specs = parse_coordinates(args.coordinate)
        data_configs, configs = configs_from_specs(specs)
        update_sequence = (
            tuple(s.strip() for s in args.update_sequence.split(","))
            if args.update_sequence
            else tuple(c.cid for c in specs)
        )
        shard_specs = [
            parse_feature_shard(s)
            for s in (args.feature_shard or ["global:features"])
        ]
        needed = {c.feature_shard for c in data_configs.values()}
        have = {s.shard for s in shard_specs}
        if needed - have:
            raise ValueError(
                f"coordinates use feature shards {sorted(needed - have)} with no "
                f"--feature-shard spec (have {sorted(have)})"
            )

        shard_cfgs, index_maps = _load_or_build_indexes(args, shard_specs, logger)

        id_tags = sorted(
            {
                c.re_type
                for c in data_configs.values()
                if isinstance(c, RandomEffectDataConfig)
            }
            | {
                ev.group_column
                for ev in (
                    EvaluationSuite.parse(args.evaluators).evaluators
                    if args.evaluators
                    else ()
                )
                if ev.group_column
            }
        )
        from photon_tpu.io.data_reader import InputColumnNames

        reader = AvroDataReader(
            index_maps,
            shard_cfgs,
            columns=InputColumnNames(
                uid=args.uid_column,
                response=args.response_column,
                offset=args.offset_column,
                weight=args.weight_column,
            ),
            id_tag_columns=id_tags,
        )

        read_dtype = np.float64 if args.dtype == "float64" else np.float32
        if args.bf16_feed and args.dtype == "float64":
            raise ValueError(
                "--bf16-feed narrows the device feed below float32; it "
                "cannot honor --dtype float64 (pick one)"
            )
        feed_dtype = "bfloat16" if args.bf16_feed else None

        # ONE streaming reader for every pipelined read: its compiled decode
        # programs + per-shard probe tables are config-determined and reused
        # across the train AND validation reads (the old AvroDataReader path
        # made the same guarantee via its cached self._streaming).
        from photon_tpu.io.streaming import StreamingAvroReader

        stream_reader = StreamingAvroReader(
            index_maps, shard_cfgs, reader.columns, id_tags,
            capture_uids=False,
        )

        def read_data(paths):
            from photon_tpu.io.prefetch import (
                default_prefetch_depth,
                read_bundle_pipelined,
            )
            from photon_tpu.io.streaming import Unsupported

            depth = (default_prefetch_depth() if args.prefetch_depth is None
                     else max(0, args.prefetch_depth))
            # Training never reads the uid column; skipping it keeps host
            # memory at the numeric floor (10^8 uid strings would dwarf the
            # ELL arrays themselves).
            try:
                # Pipelined ingest→device path (io/prefetch.py): background
                # block decode (+ the worker pool under --ingest-workers)
                # overlapped with bundle assembly and the device upload;
                # --bf16-feed narrows feature values on the host first.
                return read_bundle_pipelined(
                    index_maps, shard_cfgs, reader.columns, id_tags, paths,
                    dtype=read_dtype, depth=depth,
                    workers=args.ingest_workers, capture_uids=False,
                    feed_dtype=feed_dtype, reader=stream_reader,
                )
            except Unsupported as e:
                logger.info("pipelined ingest unavailable (%s); "
                            "per-record read", e)
            bundle = reader.read(paths, dtype=read_dtype, capture_uids=False)
            if feed_dtype is not None:
                logger.info("--bf16-feed inactive on the per-record "
                            "fallback reader (values stay %s)", read_dtype)
            return bundle

        with Timed("read training data", logger) as t:
            train = read_data(args.train_data)
        logger.info("training rows: %d", train.n_rows)
        validation = None
        if args.validation_data:
            with Timed("read validation data", logger):
                validation = read_data(args.validation_data)
            logger.info("validation rows: %d", validation.n_rows)

        vtype = DataValidationType[args.data_validation]
        with Timed("data validation", logger):
            for shard in needed:
                sanity_check_data(train.batch(shard), task, vtype)

        if args.feature_summary:
            from photon_tpu.data.statistics import compute_feature_statistics
            from photon_tpu.io.model_io import save_feature_summary

            with Timed("feature summarization", logger):
                for shard in sorted(needed):
                    stats = compute_feature_statistics(train.batch(shard))
                    save_feature_summary(
                        os.path.join(args.output_dir, "summary",
                                     f"{shard}.avro"),
                        index_maps[shard], stats,
                    )
                    logger.info("feature summary[%s]: %d features", shard,
                                stats.dim)

        initial_model = None
        if args.model_input_dir:
            from photon_tpu.io.model_io import load_game_model

            with Timed("load warm-start model", logger):
                initial_model, _ = load_game_model(
                    args.model_input_dir, index_maps, dtype=read_dtype
                )

        from photon_tpu.cli.params import mesh_from_flags

        mesh = mesh_from_flags(args.devices, args.mesh)
        if mesh is not None:
            logger.info("mesh: %s", mesh)
        model_axis = (
            "model" if mesh is not None and "model" in mesh.shape else None
        )

        estimator = GameEstimator(
            task=task,
            coordinate_data_configs=data_configs,
            update_sequence=update_sequence,
            n_sweeps=args.sweeps,
            evaluator_specs=tuple(args.evaluators or ()),
            normalization=NormalizationType[args.normalization],
            intercept_indices={
                s: im.intercept_index for s, im in index_maps.items()
            },
            mesh=mesh,
            model_axis=model_axis,
            sweep_cache_mb=args.sweep_cache_mb,
        )

        if args.tuning:
            if not (args.evaluators and validation is not None):
                raise ValueError("--tuning needs --evaluators and --validation-data")
            if not args.tuning_range:
                raise ValueError("--tuning needs at least one --tuning-range CID:MIN:MAX")
            if args.tuning_iterations < 1:
                raise ValueError(
                    f"--tuning-iterations must be >= 1, got {args.tuning_iterations}"
                )
            if len(configs) > 1:
                raise ValueError(
                    "--tuning replaces the reg-weight grid sweep; remove the "
                    "multi-value reg_weights axes from --coordinate specs"
                )
            from photon_tpu.hyperparameter import tune_regularization

            ranges = {}
            for spec in args.tuning_range:
                cid, lo, hi = spec.split(":")
                ranges[cid] = (float(lo), float(hi))
            with _checkpointing(args.checkpoint_dir) as tuning_ckpt, \
                    Timed("hyperparameter tuning", logger) as fit_timer:
                tuning = tune_regularization(
                    estimator, train, validation, configs[0], ranges,
                    n_iterations=args.tuning_iterations,
                    strategy=args.tuning, seed=0,
                    initial_model=initial_model,
                    checkpoint_manager=tuning_ckpt,
                )
            logger.info(
                "tuning best params %s -> %.6g",
                dict(zip(sorted(ranges), tuning.best_params)),
                tuning.search.best_value,
            )
            # The best config's model was already trained during the search.
            results = [tuning.best_result]
        else:
            with _checkpointing(args.checkpoint_dir) as ckpt, \
                    Timed("fit", logger) as fit_timer:
                results = estimator.fit(
                    train,
                    validation if args.evaluators else None,
                    configs,
                    initial_model=initial_model,
                    checkpoint_manager=ckpt,
                )

        suite = (
            EvaluationSuite.parse(args.evaluators) if args.evaluators else None
        )
        best = select_best(results, suite) if suite else results[0]
        # Identity, not equality: results hold JAX arrays whose __eq__ is
        # elementwise, so list.index would raise on any non-first best.
        best_i = next(i for i, r in enumerate(results) if r is best)

        shard_by_coordinate = {
            cid: c.feature_shard for cid, c in data_configs.items()
        }
        saved = {}
        with Timed("save models", logger):
            if args.output_mode == "ALL":
                for i, r in enumerate(results):
                    mdir = os.path.join(args.output_dir, "models", str(i))
                    save_game_model(mdir, r.model, index_maps,
                                    shard_by_coordinate, shard_cfgs)
                    saved[str(i)] = mdir
            bdir = os.path.join(args.output_dir, "best")
            save_game_model(bdir, best.model, index_maps,
                            shard_by_coordinate, shard_cfgs)
            saved["best"] = bdir
            for shard, im in index_maps.items():
                idir = os.path.join(args.output_dir, "index", shard)
                if isinstance(im, MmapIndexMap):
                    # already a store on disk: copy it so the output dir is a
                    # self-contained scoring input
                    if not os.path.exists(idir):
                        import shutil

                        shutil.copytree(im._dir, idir)
                else:
                    build_mmap_index(im, idir)

        summary = {
            "task": task.name,
            "n_configs": len(results),
            "best_config_index": best_i,
            "best_config": {
                cid: dataclasses.asdict(best.config[cid])
                for cid in best.config
            },
            "evaluation": dict(best.evaluation.values) if best.evaluation else None,
            "fit_seconds": fit_timer.seconds,
            "model_dirs": saved,
        }
        # enums are not JSON-serializable through asdict
        summary = json.loads(json.dumps(summary, default=lambda o: getattr(o, "name", str(o))))
        with open(os.path.join(args.output_dir, "training-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        write_metrics_jsonl(
            os.path.join(args.output_dir, "metrics.jsonl"),
            (
                {
                    "config": i,
                    "sweep": rec.sweep,
                    "coordinate": rec.coordinate_id,
                    "seconds": rec.seconds,
                    **(rec.validation.values if rec.validation else {}),
                }
                for i, r in enumerate(results)
                for rec in r.tracker
            ),
        )
        logger.info("done; best config %d, evaluation %s", best_i, summary["evaluation"])
        return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
