"""Online incremental training driver (docs/online.md).

The sixth driver: where the training driver batch-fits a model directory
and the serving driver scores it, this one sits BETWEEN them — it tails an
event log, re-solves dirty entities on a cadence, and publishes model
deltas into a live scoring server:

    python -m photon_tpu.cli.online_training_driver \\
        --model-dir out/best --events events.jsonl \\
        --serve-url http://127.0.0.1:8080 --output-dir online_out --follow

Without ``--serve-url`` the trainer runs open-loop (state + patch journal
advance, nothing served) — the shadow-evaluation mode. The replay cursor
(``<output-dir>/online-cursor.json``) advances only past PUBLISHED events,
so a restarted driver resumes exactly where its last delta left off.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

from photon_tpu.utils import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="online-training-driver",
        description="Stream events into per-entity model deltas published "
                    "to a live GAME scoring server.",
    )
    p.add_argument("--model-dir", required=True,
                   help="a 'best' or 'models/<i>' directory from the "
                        "training driver: the base model whose fixed "
                        "effects freeze and whose random-effect posteriors "
                        "seed the refresh anchors")
    p.add_argument("--index-dir", default=None,
                   help="per-shard index stores (default: "
                        "<model-dir>/../index)")
    p.add_argument("--events", required=True,
                   help="JSONL event log (docs/online.md §schema)")
    p.add_argument("--serve-url", default=None,
                   help="live scoring server base URL; deltas publish via "
                        "POST /admin/patch (omit to run open-loop)")
    p.add_argument("--delta-log", default=None,
                   help="durable delta log (JSONL) to append every "
                        "published delta to — the write-once fan-out N "
                        "serving replicas tail (docs/serving.md "
                        "§'Replication'); combinable with --serve-url "
                        "(both must succeed per publish)")
    p.add_argument("--canary-log", default=None,
                   help="publish deltas into this CANARY side-channel log "
                        "instead of the mainline --delta-log; only the "
                        "designated canary replica tails it, and the "
                        "control driver promotes soaked waves into the "
                        "main log (docs/control.md §'Canary protocol'). "
                        "Mutually exclusive with --delta-log")
    p.add_argument("--publish-retries", type=int, default=3,
                   help="bounded retries (decorrelated-jitter backoff) "
                        "for --serve-url publishes hitting transient "
                        "connection errors or 503 sheds")
    p.add_argument("--output-dir", default=None,
                   help="photon.log + patch-journal.jsonl + "
                        "online-cursor.json land here")
    p.add_argument("--window", type=int, default=64,
                   help="sliding-window rows per entity (the refresh's "
                        "training data)")
    p.add_argument("--max-event-nnz", type=int, default=64,
                   help="per-shard feature cap per event (stable-shape "
                        "contract; over-cap events are rejected)")
    p.add_argument("--refresh-batch", type=int, default=4096,
                   help="dirty entities per refresh cycle")
    p.add_argument("--chunk", type=int, default=256,
                   help="blessed entity-chunk size for the batched Newton "
                        "solves (must be on PHOTON_RE_CHUNK_LADDER)")
    p.add_argument("--cadence-s", type=float, default=1.0,
                   help="refresh cadence in seconds (0 = only when "
                        "refresh-batch entities are dirty, or at drain)")
    p.add_argument("--incremental-weight", type=float, default=1.0,
                   help="Gaussian-prior anchor strength to the previous "
                        "posterior (0 = plain warm start, no anchoring)")
    p.add_argument("--reg-weight", type=float, default=1.0,
                   help="L2 weight per refresh solve")
    p.add_argument("--max-iter", type=int, default=30)
    p.add_argument("--tol", type=float, default=1e-7)
    p.add_argument("--max-cycles", type=int, default=0,
                   help="stop after N refresh cycles (0 = run to stream "
                        "end / until interrupted under --follow)")
    p.add_argument("--follow", action="store_true",
                   help="tail the event log instead of stopping at EOF")
    p.add_argument("--no-resume", action="store_true",
                   help="ignore the saved replay cursor and start from "
                        "event seq 0")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="read events through the bounded background "
                        "prefetch stage (io/prefetch.py; default "
                        "$PHOTON_PREFETCH_DEPTH, 0 disables)")
    from photon_tpu.cli.params import (
        add_backend_policy_flag,
        add_compilation_cache_flag,
        add_compile_store_flag,
        add_fault_plan_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_backend_policy_flag(p)
    add_compilation_cache_flag(p)
    add_compile_store_flag(p)
    add_fault_plan_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def _load_base(args, logger):
    """Model dir → (GameModel, data configs, index maps, shard configs) —
    the same metadata reconstruction the serving registry does, so the
    trainer and the server can never disagree about feature assembly."""
    from photon_tpu.estimators import (
        FixedEffectDataConfig,
        RandomEffectDataConfig,
    )
    from photon_tpu.index.index_map import MmapIndexMap
    from photon_tpu.io.data_reader import FeatureShardConfig
    from photon_tpu.io.model_io import default_index_root, load_game_model

    with open(os.path.join(args.model_dir, "game-metadata.json")) as f:
        meta = json.load(f)
    shards = {info["feature_shard"] for info in meta["coordinates"].values()}
    index_root = args.index_dir or default_index_root(args.model_dir)
    index_maps = {
        s: MmapIndexMap(os.path.join(index_root, s)) for s in sorted(shards)
    }
    for im in index_maps.values():
        im.preload()
    model, meta = load_game_model(args.model_dir, index_maps)
    data_configs = {}
    for cid, info in meta["coordinates"].items():
        if info["type"] == "fixed":
            data_configs[cid] = FixedEffectDataConfig(info["feature_shard"])
        else:
            data_configs[cid] = RandomEffectDataConfig(
                re_type=info["re_type"], feature_shard=info["feature_shard"]
            )
    saved_shards = meta.get("feature_shards", {})
    shard_configs = {
        s: (
            FeatureShardConfig(
                feature_bags=tuple(saved_shards[s]["feature_bags"]),
                add_intercept=saved_shards[s]["add_intercept"],
            )
            if s in saved_shards
            else FeatureShardConfig(feature_bags=("features",))
        )
        for s in index_maps
    }
    logger.info(
        "online base model: %s (%d coordinates, shards: %s)",
        args.model_dir, len(data_configs), ",".join(sorted(index_maps)),
    )
    return model, data_configs, index_maps, shard_configs


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import finish_telemetry, finish_trace

    try:
        return _run(args)
    finally:
        finish_trace(args.trace_out)
        finish_telemetry(args)


def _run(args) -> dict:
    from photon_tpu.cli.params import (
        enable_backend_guard,
        enable_compilation_cache,
        enable_compile_store,
        enable_fault_plan,
        enable_telemetry,
        enable_trace,
    )
    from photon_tpu.io.prefetch import prefetch
    from photon_tpu.online import (
        EventCursor,
        HttpPublisher,
        OnlineTrainer,
        OnlineTrainerConfig,
        PatchJournal,
        iter_events,
    )

    enable_backend_guard(args)
    enable_compilation_cache(args.compilation_cache_dir)
    # Opt-in AOT compile store: the fixed-ladder refresh kernels record at
    # first compile, so a device-loss recovery's cache clear repopulates
    # by LOADING instead of retracing (docs/robustness.md §"Recovery
    # time"). Opt-in (flag/env), like the serving driver.
    if getattr(args, "compile_store", None):
        enable_compile_store(args, output_dir=args.output_dir)
    enable_fault_plan(args.fault_plan)
    enable_telemetry(args, role="online")
    enable_trace(args.trace_out)
    plogger = PhotonLogger(args.output_dir)
    logger = plogger.logger

    model, data_configs, index_maps, shard_configs = _load_base(args, logger)
    config = OnlineTrainerConfig(
        window=args.window,
        max_event_nnz=args.max_event_nnz,
        refresh_batch=args.refresh_batch,
        chunk=args.chunk,
        cadence_s=args.cadence_s,
        incremental_weight=args.incremental_weight,
        reg_weight=args.reg_weight,
        max_iterations=args.max_iter,
        tolerance=args.tol,
    )
    # Publisher fan-out: the point-to-point HTTP push (legacy single
    # server) and the durable delta log (the replicated tier's write-once
    # path) compose — a delta is "published" only when every sink took it.
    sinks = []
    if args.serve_url:
        sinks.append(HttpPublisher(args.serve_url,
                                   retries=args.publish_retries))
    if getattr(args, "delta_log", None) and getattr(args, "canary_log",
                                                    None):
        raise SystemExit(
            "--delta-log and --canary-log are mutually exclusive: under "
            "canary control the CONTROLLER owns the main log (waves reach "
            "it only by promotion)")
    # Under canary control the trainer writes the SIDE CHANNEL only; the
    # control driver owns the main log and appends promoted waves there.
    wave_log = (getattr(args, "canary_log", None)
                or getattr(args, "delta_log", None))
    if wave_log:
        from photon_tpu.replication import DeltaLogPublisher

        sinks.append(DeltaLogPublisher(
            wave_log, snapshot_model_dir=args.model_dir))
    if len(sinks) > 1:
        from photon_tpu.replication import FanoutPublisher

        publisher = FanoutPublisher(*sinks)
    else:
        publisher = sinks[0] if sinks else None
    journal = PatchJournal(args.output_dir) if args.output_dir else None
    cursor = EventCursor(args.output_dir) if args.output_dir else None
    trainer = OnlineTrainer.from_game_model(
        model, data_configs, index_maps, shard_configs, config,
        publisher=publisher, journal=journal, cursor=cursor,
    )
    start_seq = 0
    if cursor is not None and not args.no_resume:
        start_seq = cursor.load()
        if start_seq:
            logger.info("resuming event replay at seq %d (cursor)",
                        start_seq)
    events = iter_events(
        args.events, start_seq=start_seq, follow=args.follow,
        # Idle ticks on a quiet followed stream: the cadence must still
        # fire with dirty entities pending, not block until the next event.
        idle_yield_s=args.cadence_s if args.follow else 0.0,
    )
    # Background tailing through the bounded prefetch stage: event decode
    # and the refresh solves overlap, same pipeline shape as training
    # ingest (io/prefetch.py).
    events = prefetch(events, depth=args.prefetch_depth)
    try:
        summary = trainer.run(
            events, max_cycles=args.max_cycles or None,
        )
    except KeyboardInterrupt:
        summary = {**trainer.totals, "interrupted": True}
    summary = {
        "model_dir": args.model_dir,
        "events_path": args.events,
        "serve_url": args.serve_url,
        "delta_log": getattr(args, "delta_log", None),
        "canary_log": getattr(args, "canary_log", None),
        "start_seq": start_seq,
        **{k: v for k, v in summary.items() if k != "refreshes"},
    }
    logger.info("online trainer done: %s", json.dumps(summary))
    if args.output_dir:
        with open(os.path.join(args.output_dir,
                               "online-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
    plogger.close()
    return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
