"""Closed-loop control plane driver (docs/control.md).

The eighth driver: where the router fronts replicas and the supervisor
restarts processes, this one closes the loop ABOVE them — it ticks a
:class:`~photon_tpu.control.Controller` that observes live replica
telemetry, matches it against a declarative policy (anomaly→action
rules, canary soak gates, damped autoscaling), actuates pre-existing
levers over HTTP, and journals every decision to
``control-ledger.jsonl``:

    python -m photon_tpu.cli.control_driver \\
        --replica http://127.0.0.1:8081 --canary http://127.0.0.1:8082 \\
        --delta-log main/delta-log.jsonl \\
        --canary-log online/delta-log.canary.jsonl \\
        --model-dir out/best --output-dir control_out --max-ticks 30

Deliberately accelerator-free, like the router: the controller never
imports jax — it must keep deciding while every replica behind it is
busy recompiling or recovering.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

from photon_tpu.utils import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="control-driver",
        description="Closed-loop controller: anomaly→action policies, "
                    "canary delta publication with auto-rollback, and "
                    "damped serving autoscaling.",
    )
    p.add_argument("--replica", action="append", default=None,
                   metavar="URL", dest="replicas",
                   help="traffic-bearing replica base URL (repeatable)")
    p.add_argument("--canary", default=None, metavar="URL",
                   help="the designated canary replica (at most one); "
                        "requires --delta-log, --canary-log and "
                        "--model-dir")
    p.add_argument("--delta-log", default=None,
                   help="MAIN delta log — the controller owns its writer "
                        "and appends promoted canary waves to it")
    p.add_argument("--canary-log", default=None,
                   help="canary side-channel log the online trainer "
                        "publishes waves into (its --canary-log)")
    p.add_argument("--model-dir", default=None,
                   help="base model directory: the rollback / standby-swap "
                        "target")
    p.add_argument("--policy", default=None,
                   help="ControlPolicy JSON file (default: built-in "
                        "defaults; see docs/control.md §policy schema)")
    p.add_argument("--probe", default=None,
                   help="JSON file with scoring rows for the per-tick "
                        "latency probe and the canary drift probe "
                        "(without it the controller falls back to "
                        "/healthz round-trips and health-only canary "
                        "verdicts)")
    p.add_argument("--router", default=None, metavar="URL",
                   help="router base URL (recorded in the ledger for the "
                        "fleet report's topology join)")
    p.add_argument("--tick", type=float, default=None,
                   help="override the policy's tick interval in seconds")
    p.add_argument("--max-ticks", type=int, default=0,
                   help="stop after N ticks (0 = run until interrupted)")
    p.add_argument("--restart-budget", type=int, default=3,
                   help="max tailer-restart grants per replica "
                        "(supervisor RestartPolicy pacing; 0 disables "
                        "the restart_tailer lever's budget gate)")
    p.add_argument("--lever-timeout", type=float, default=10.0,
                   help="per-lever HTTP deadline in seconds")
    p.add_argument("--output-dir", default=None,
                   help="photon.log + control-ledger.jsonl land here "
                        "(default: cwd for the ledger)")
    from photon_tpu.cli.params import (
        add_fault_plan_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_fault_plan_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import finish_trace

    try:
        return _run(args)
    finally:
        finish_trace(args.trace_out)


def _run(args) -> dict:
    from photon_tpu.cli.params import (
        enable_fault_plan,
        enable_telemetry,
        enable_trace,
        finish_telemetry,
    )
    from photon_tpu.control import (
        ControlLedger,
        ControlPolicy,
        Controller,
        LEDGER_FILENAME,
        Levers,
        ReplicaTarget,
    )

    replicas = [ReplicaTarget(u) for u in (args.replicas or ())]
    if args.canary:
        replicas.append(ReplicaTarget(args.canary, canary=True))
    if not replicas:
        raise SystemExit("control-driver: at least one --replica or "
                         "--canary required")
    if args.canary and not (args.delta_log and args.canary_log
                            and args.model_dir):
        raise SystemExit("control-driver: --canary requires --delta-log, "
                         "--canary-log and --model-dir")
    enable_fault_plan(args.fault_plan)
    enable_telemetry(args, role="control")
    enable_trace(args.trace_out)
    plogger = PhotonLogger(args.output_dir)
    logger = plogger.logger

    if args.policy:
        policy = ControlPolicy.from_file(args.policy)
    else:
        policy = ControlPolicy()
    if args.tick is not None:
        import dataclasses

        policy = dataclasses.replace(policy, tick_s=args.tick)
    probe_rows = None
    if args.probe:
        with open(args.probe) as f:
            probe_rows = json.load(f)
        if not isinstance(probe_rows, list):
            raise SystemExit("control-driver: --probe must be a JSON "
                             "list of scoring rows")
    restart_policy = None
    if args.restart_budget > 0:
        from photon_tpu.supervisor import RestartPolicy

        restart_policy = RestartPolicy(max_restarts=args.restart_budget)

    ledger_dir = args.output_dir or "."
    os.makedirs(ledger_dir, exist_ok=True)
    ledger = ControlLedger(os.path.join(ledger_dir, LEDGER_FILENAME))
    controller = Controller(
        policy,
        replicas,
        ledger,
        main_log_path=args.delta_log,
        canary_log_path=args.canary_log,
        base_model_dir=args.model_dir,
        probe_rows=probe_rows,
        router_url=args.router,
        levers=Levers(timeout_s=args.lever_timeout),
        restart_policy=restart_policy,
        logger=logger,
    )
    logger.info(
        "control loop over %d replica(s)%s: policy %s, tick %.3gs%s",
        len(replicas),
        f" (canary {args.canary})" if args.canary else "",
        policy.digest(), policy.tick_s,
        f", max_ticks={args.max_ticks}" if args.max_ticks else "")

    def _graceful(signum, frame):
        raise KeyboardInterrupt

    try:
        import signal

        # SIGTERM routes through the same graceful stop as Ctrl-C, same
        # contract as the serving and router drivers. Main-thread only.
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:
        pass
    try:
        controller.run(max_ticks=args.max_ticks or None)
    except KeyboardInterrupt:
        controller._stop.set()
    finally:
        finish_telemetry(args, registries=(controller.metrics,))
    summary = {
        "replicas": [r.url for r in replicas],
        "canary": args.canary,
        "ticks": controller.ticks,
        "actions": controller.actions_total,
        "policy_digest": policy.digest(),
        "ledger": os.path.abspath(ledger.path),
    }
    logger.info("control loop done: %s", json.dumps(summary))
    if args.output_dir:
        with open(os.path.join(args.output_dir,
                               "control-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
    plogger.close()
    return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
