"""Streaming fleet-view driver (docs/observability.md §"Live fleet view").

The ninth driver: where ``python -m photon_tpu.obs.analysis report``
fuses a run's telemetry AFTER every process has exited, this one tails
the same ``--telemetry-dir`` while the fleet is still running — merging
registry shards incrementally, folding metrics JSONL histories through
the run report's median/MAD level-shift detector at the live edge, and
serving the continuously refreshed fleet state over HTTP:

    python -m photon_tpu.cli.obs_driver \\
        --telemetry-dir /tmp/fleet --port 8090 --interval 2

    curl -s localhost:8090/fleet              # JSON fleet state
    curl -s localhost:8090/fleet?format=md    # rendered run report

Deliberately accelerator-free, same contract as the router and control
drivers: the observer must keep answering while every serving process
behind it is recompiling, recovering, or dead — that is exactly when the
fleet view matters most.
"""
from __future__ import annotations

import argparse
import json
from typing import Optional, Sequence

from photon_tpu.utils import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="obs-driver",
        description="Serve a live, continuously refreshed fleet view "
                    "(merged metrics + streaming anomaly detection) over "
                    "a shared telemetry directory.",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8090,
                   help="0 binds an ephemeral port (logged at startup)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between telemetry-dir refresh ticks")
    p.add_argument("--window", type=int, default=None,
                   help="trailing window for the level-shift detector "
                        "(default: the run report's, 16)")
    p.add_argument("--z-threshold", type=float, default=None,
                   help="robust z-score threshold (default: 6.0)")
    p.add_argument("--min-history", type=int, default=None,
                   help="predecessors required before a point scores "
                        "(default: 8)")
    p.add_argument("--min-run", type=int, default=None,
                   help="consecutive over-threshold points that make a "
                        "level shift (default: 2; lone spikes are noise)")
    p.add_argument("--metric", action="append", default=None,
                   metavar="DOTTED", dest="metrics",
                   help="flattened metric path to watch, repeatable "
                        "(default: latency.p50_ms/p95_ms/p99_ms)")
    p.add_argument("--report-top", type=int, default=5,
                   help="rows per section in the embedded run report")
    p.add_argument("--output-dir", default=None,
                   help="photon.log lands here")
    from photon_tpu.cli.params import add_telemetry_flag, add_trace_flag

    # --telemetry-dir does double duty here: it is the directory this
    # driver WATCHES, and (per the shared convention) where its own
    # trace/registry shards land at exit — the observer shows up in the
    # post-hoc fleet report like any other role.
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def run(argv: Optional[Sequence[str]] = None,
        serve_forever: bool = True) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import finish_trace

    try:
        return _run(args, serve_forever)
    finally:
        finish_trace(args.trace_out)


def _run(args, serve_forever: bool) -> dict:
    from photon_tpu.cli.params import (
        enable_telemetry,
        enable_trace,
        finish_telemetry,
    )
    from photon_tpu.obs.live import LiveFleetServer

    if not getattr(args, "telemetry_dir", None):
        raise SystemExit("obs-driver: --telemetry-dir required "
                         "(the directory to watch)")
    telemetry_dir = enable_telemetry(args, role="obs")
    enable_trace(args.trace_out)
    plogger = PhotonLogger(args.output_dir)
    logger = plogger.logger
    kwargs = {}
    for flag, key in (("window", "window"), ("z_threshold", "z_threshold"),
                      ("min_history", "min_history"),
                      ("min_run", "min_run")):
        v = getattr(args, flag)
        if v is not None:
            kwargs[key] = v
    server = LiveFleetServer(
        telemetry_dir,
        host=args.host,
        port=args.port,
        interval_s=args.interval,
        logger=logger,
        metrics=args.metrics,
        report_top=args.report_top,
        **kwargs,
    )
    summary = {
        "address": list(server.address),
        "telemetry_dir": server.watcher.run_dir,
        "interval_s": args.interval,
        "watch_metrics": list(server.watcher.watch_metrics),
    }
    logger.info("live fleet view on http://%s:%d watching %s: %s",
                *server.address, server.watcher.run_dir,
                json.dumps(summary))
    if not serve_forever:
        # Smoke/integration entry: one synchronous tick so the summary
        # reflects a real pass over the directory, then tear down.
        state = server.watcher.tick()
        server.shutdown()
        summary["roles"] = state.get("roles", [])
        summary["n_live_anomalies"] = state.get("n_live_anomalies", 0)
        finish_telemetry(args)
        plogger.close()
        return summary

    def _graceful(signum, frame):
        raise KeyboardInterrupt

    try:
        import signal

        # SIGTERM routes through the same graceful stop as Ctrl-C, same
        # contract as the other drivers. Main-thread only.
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:
        pass
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        state = server.watcher.state()
        summary["ticks"] = state.get("ticks", 0)
        summary["roles"] = state.get("roles", [])
        summary["n_live_anomalies"] = state.get("n_live_anomalies", 0)
        # Only this process's own registry: exporting the FOLDED fleet
        # registry back into the directory it was folded from would
        # double-count every other role's metrics on the next merge.
        finish_telemetry(args)
        plogger.close()
    return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
