"""GAME scoring driver: load a model directory + data → scores Avro.

Parity: reference ⟦photon-client/.../cli/game/scoring/GameScoringDriver.scala⟧
(SURVEY.md §3.6): read data through the SAME index maps the model was trained
with, load the GAME model, score additively per coordinate (unseen entities →
zero model), write ``ScoringResultAvro`` records, optionally evaluate.

The model directory written by the training driver carries its index maps
(``<output>/index/<shard>``) and per-coordinate metadata, so only
``--model-dir`` and data paths are required.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

import numpy as np

from photon_tpu.estimators import (
    FixedEffectDataConfig,
    GameTransformer,
    RandomEffectDataConfig,
)
from photon_tpu.evaluation import EvaluationSuite
from photon_tpu.index.index_map import MmapIndexMap
from photon_tpu.io.data_reader import (
    AvroDataReader,
    FeatureShardConfig,
    InputColumnNames,
)
from photon_tpu.io.model_io import load_game_model, save_scores
from photon_tpu.utils import PhotonLogger, Timed


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-scoring-driver",
        description="Score data with a trained GAME model.",
    )
    p.add_argument("--data", nargs="+", required=True)
    p.add_argument("--model-dir", required=True,
                   help="a 'best' or 'models/<i>' directory from the training driver")
    p.add_argument("--index-dir", default=None,
                   help="per-shard index stores (default: <model-dir>/../index)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--evaluators", nargs="+", default=None,
                   help="optional evaluator specs over the scored data")
    p.add_argument("--feature-bags", nargs="+", default=["features"],
                   help="record fields holding feature lists (per training config)")
    p.add_argument("--response-column", default="response")
    p.add_argument("--uid-column", default="uid")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"],
                   help="scoring precision (float64 enables jax x64)")
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    _dt = np.float64 if args.dtype == "float64" else np.float32
    os.makedirs(args.output_dir, exist_ok=True)
    with PhotonLogger(args.output_dir) as logger:
        with open(os.path.join(args.model_dir, "game-metadata.json")) as f:
            meta = json.load(f)
        shards = {info["feature_shard"] for info in meta["coordinates"].values()}

        if args.index_dir:
            index_root = args.index_dir
        else:
            # The training driver writes indexes at <out>/index while models
            # live at <out>/best or <out>/models/<i> — walk up past "models",
            # but only for true models/<i> children (an output dir itself
            # named "models" must not trigger the walk-up).
            norm = os.path.normpath(args.model_dir)
            parent = os.path.dirname(norm)
            if (os.path.basename(parent) == "models"
                    and os.path.basename(norm).isdigit()):
                parent = os.path.dirname(parent)
            index_root = os.path.join(parent, "index")
        index_maps = {
            s: MmapIndexMap(os.path.join(index_root, s)) for s in sorted(shards)
        }
        with Timed("load model", logger):
            model, meta = load_game_model(args.model_dir, index_maps, dtype=_dt)

        # Reconstruct per-coordinate data configs from model metadata.
        data_configs = {}
        id_tags = set()
        for cid, info in meta["coordinates"].items():
            if info["type"] == "fixed":
                data_configs[cid] = FixedEffectDataConfig(info["feature_shard"])
            else:
                data_configs[cid] = RandomEffectDataConfig(
                    re_type=info["re_type"], feature_shard=info["feature_shard"]
                )
                id_tags.add(info["re_type"])

        suite = EvaluationSuite.parse(args.evaluators) if args.evaluators else None
        if suite:
            id_tags |= {
                ev.group_column for ev in suite.evaluators if ev.group_column
            }

        # Shard configs persisted at training time are authoritative; the
        # --feature-bags flag is only a fallback for pre-metadata models.
        saved_shards = meta.get("feature_shards", {})
        shard_cfgs = {
            s: (
                FeatureShardConfig(
                    feature_bags=tuple(saved_shards[s]["feature_bags"]),
                    add_intercept=saved_shards[s]["add_intercept"],
                )
                if s in saved_shards
                else FeatureShardConfig(feature_bags=tuple(args.feature_bags))
            )
            for s in index_maps
        }
        reader = AvroDataReader(
            index_maps,
            shard_cfgs,
            columns=InputColumnNames(
                uid=args.uid_column, response=args.response_column
            ),
            id_tag_columns=sorted(id_tags),
        )
        with Timed("read data", logger):
            # Labels are only required when evaluators were requested.
            bundle = reader.read(args.data, require_labels=suite is not None,
                                 dtype=_dt)
        logger.info("scoring %d rows", bundle.n_rows)

        transformer = GameTransformer(
            model,
            data_configs,
            intercept_indices={
                s: im.intercept_index for s, im in index_maps.items()
            },
        )
        evaluation = None
        with Timed("score", logger):
            if suite:
                scores, evaluation = transformer.transform_and_evaluate(
                    bundle, suite
                )
            else:
                scores = transformer.transform(bundle)

        with Timed("save scores", logger):
            save_scores(
                os.path.join(args.output_dir, "scores.avro"),
                np.asarray(scores),
                uids=bundle.uids,
                labels=bundle.labels,
            )
        summary = {
            "n_rows": int(bundle.n_rows),
            "evaluation": dict(evaluation.values) if evaluation else None,
        }
        with open(os.path.join(args.output_dir, "scoring-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        logger.info("done: %s", summary)
        return summary


def main() -> None:  # pragma: no cover - console entry
    run()


if __name__ == "__main__":  # pragma: no cover
    main()
