"""GAME scoring driver: load a model directory + data → scores Avro.

Parity: reference ⟦photon-client/.../cli/game/scoring/GameScoringDriver.scala⟧
(SURVEY.md §3.6): read data through the SAME index maps the model was trained
with, load the GAME model, score additively per coordinate (unseen entities →
zero model), write ``ScoringResultAvro`` records, optionally evaluate.

The model directory written by the training driver carries its index maps
(``<output>/index/<shard>``) and per-coordinate metadata, so only
``--model-dir`` and data paths are required.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

import numpy as np

from photon_tpu.estimators import (
    FixedEffectDataConfig,
    GameTransformer,
    RandomEffectDataConfig,
)
from photon_tpu.evaluation import EvaluationSuite
from photon_tpu.index.index_map import MmapIndexMap
from photon_tpu.io.data_reader import (
    AvroDataReader,
    FeatureShardConfig,
    InputColumnNames,
)
from photon_tpu.io.model_io import load_game_model, save_scores
from photon_tpu.utils import PhotonLogger, Timed


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-scoring-driver",
        description="Score data with a trained GAME model.",
    )
    p.add_argument("--data", nargs="+", required=True)
    p.add_argument("--model-dir", required=True,
                   help="a 'best' or 'models/<i>' directory from the training driver")
    p.add_argument("--index-dir", default=None,
                   help="per-shard index stores (default: <model-dir>/../index)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--evaluators", nargs="+", default=None,
                   help="optional evaluator specs over the scored data")
    p.add_argument("--feature-bags", nargs="+", default=["features"],
                   help="record fields holding feature lists (per training config)")
    p.add_argument("--response-column", default="response")
    p.add_argument("--uid-column", default="uid")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"],
                   help="scoring precision (float64 enables jax x64)")
    p.add_argument("--devices", type=int, default=1,
                   help="shard the fixed-effect scoring matvec's rows over "
                        "this many devices (0 = all visible); 1 = no mesh")
    p.add_argument("--chunk-rows", type=int, default=0,
                   help="stream the data in chunks of about this many rows: "
                        "features never fully materialize in host or device "
                        "memory and scores append to the output as computed "
                        "(billion-row serve path; 0 = whole-dataset). "
                        "Evaluators still work but accumulate O(total rows) "
                        "of numeric scalars (scores/labels/weights + int32 "
                        "group codes; group-id strings are dictionary-"
                        "encoded per chunk, never accumulated)")
    from photon_tpu.cli.params import (
        add_backend_policy_flag,
        add_compilation_cache_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_backend_policy_flag(p)
    add_compilation_cache_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import (
        enable_backend_guard,
        enable_compilation_cache,
        enable_telemetry,
        enable_trace,
        finish_telemetry,
        finish_trace,
    )

    # Fail-fast backend gate (PHOTON_BACKEND_INIT_TIMEOUT_S hard deadline).
    enable_backend_guard(args)
    enable_compilation_cache(args.compilation_cache_dir)
    enable_telemetry(args, role="scoring")
    enable_trace(args.trace_out)
    try:
        return _run(args)
    finally:
        finish_trace(args.trace_out)
        finish_telemetry(args)


def _run(args) -> dict:
    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    _dt = np.float64 if args.dtype == "float64" else np.float32
    os.makedirs(args.output_dir, exist_ok=True)
    with PhotonLogger(args.output_dir) as logger:
        with open(os.path.join(args.model_dir, "game-metadata.json")) as f:
            meta = json.load(f)
        shards = {info["feature_shard"] for info in meta["coordinates"].values()}

        # Index resolution is shared with the serving registry
        # (io/model_io.default_index_root) so batch and online scoring
        # resolve a model directory identically.
        from photon_tpu.io.model_io import default_index_root

        index_root = args.index_dir or default_index_root(args.model_dir)
        index_maps = {
            s: MmapIndexMap(os.path.join(index_root, s)) for s in sorted(shards)
        }
        with Timed("load model", logger):
            model, meta = load_game_model(args.model_dir, index_maps, dtype=_dt)

        # Reconstruct per-coordinate data configs from model metadata.
        data_configs = {}
        id_tags = set()
        for cid, info in meta["coordinates"].items():
            if info["type"] == "fixed":
                data_configs[cid] = FixedEffectDataConfig(info["feature_shard"])
            else:
                data_configs[cid] = RandomEffectDataConfig(
                    re_type=info["re_type"], feature_shard=info["feature_shard"]
                )
                id_tags.add(info["re_type"])

        suite = EvaluationSuite.parse(args.evaluators) if args.evaluators else None
        if suite:
            id_tags |= {
                ev.group_column for ev in suite.evaluators if ev.group_column
            }

        # Shard configs persisted at training time are authoritative; the
        # --feature-bags flag is only a fallback for pre-metadata models.
        saved_shards = meta.get("feature_shards", {})
        shard_cfgs = {
            s: (
                FeatureShardConfig(
                    feature_bags=tuple(saved_shards[s]["feature_bags"]),
                    add_intercept=saved_shards[s]["add_intercept"],
                )
                if s in saved_shards
                else FeatureShardConfig(feature_bags=tuple(args.feature_bags))
            )
            for s in index_maps
        }
        reader = AvroDataReader(
            index_maps,
            shard_cfgs,
            columns=InputColumnNames(
                uid=args.uid_column, response=args.response_column
            ),
            id_tag_columns=sorted(id_tags),
        )
        from photon_tpu.cli.params import mesh_from_flags

        mesh = mesh_from_flags(args.devices)
        if mesh is not None:
            logger.info("scoring mesh: %s", mesh)
        transformer = GameTransformer(
            model,
            data_configs,
            intercept_indices={
                s: im.intercept_index for s, im in index_maps.items()
            },
            mesh=mesh,
            # Chunked scoring keeps stable shapes for its one-compile
            # guarantee; the layout tables' shapes are data-dependent.
            accelerator_paths=args.chunk_rows <= 0,
        )
        scores_path = os.path.join(args.output_dir, "scores.avro")
        evaluation = None
        if args.chunk_rows > 0:
            n_rows, evaluation = _score_chunked(
                args, reader, transformer, suite, scores_path, logger, _dt
            )
        else:
            with Timed("read data", logger):
                # Labels are only required when evaluators were requested.
                bundle = reader.read(
                    args.data, require_labels=suite is not None, dtype=_dt
                )
            logger.info("scoring %d rows", bundle.n_rows)
            with Timed("score", logger):
                if suite:
                    scores, evaluation = transformer.transform_and_evaluate(
                        bundle, suite
                    )
                else:
                    scores = transformer.transform(bundle)
            with Timed("save scores", logger):
                save_scores(
                    scores_path,
                    np.asarray(scores),
                    uids=bundle.uids,
                    labels=bundle.labels,
                )
            n_rows = bundle.n_rows
        summary = {
            "n_rows": int(n_rows),
            "evaluation": dict(evaluation.values) if evaluation else None,
        }
        with open(os.path.join(args.output_dir, "scoring-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        logger.info("done: %s", summary)
        return summary


def _score_chunked(args, reader, transformer, suite, scores_path, logger, _dt):
    """Stream → score → append, chunk by chunk (SURVEY.md §3.6 at the
    billion-row scale the reference serves via executor partitions).

    Features live only for the chunk being scored; rows and per-shard nnz
    widths are padded to stable shapes so XLA compiles the scoring program
    once, not per chunk. Falls back to the whole-dataset path when the
    schema is outside the streaming engine's dialect.
    """
    from photon_tpu.io.model_io import ScoresWriter
    from photon_tpu.io.streaming import StreamingAvroReader, Unsupported

    sr = StreamingAvroReader(
        reader.index_maps,
        reader.shard_configs,
        reader.columns,
        reader.id_tag_columns,
        chunk_rows=args.chunk_rows,
    )
    n_rows = 0
    k_targets: dict = {}
    acc_scores, acc_labels, acc_weights = [], [], []
    # Grouped evaluators need per-row group ids for ALL rows. Dictionary-
    # encode them incrementally per chunk (ADVICE r3): what accumulates is
    # 4 bytes/row of int32 codes + one dict entry per DISTINCT group, not
    # O(total rows) of Python string objects — scores/labels/weights remain
    # the O(rows) numeric floor any full-dataset evaluation pays.
    group_cols = {
        ev.group_column for ev in suite.evaluators if ev.group_column
    } if suite else set()
    tag_codes: dict = {col: {} for col in group_cols}
    acc_tags: dict = {col: [] for col in group_cols}

    def _encode_tags(col, values):
        cmap = tag_codes[col]
        uniq, inv = np.unique(np.asarray(values, object), return_inverse=True)
        lut = np.fromiter(
            (cmap.setdefault(u, len(cmap)) for u in uniq),
            np.int32, len(uniq),
        )
        return lut[inv.astype(np.int64)]
    with Timed("score (chunked)", logger), ScoresWriter(scores_path) as writer:
        try:
            chunks = sr.iter_chunks(
                args.data, dtype=_dt, require_labels=suite is not None
            )
            for chunk in chunks:
                for s, sf in chunk.features.items():
                    k_targets[s] = max(k_targets.get(s, 0), sf.idx.shape[1])
                # Chunks round UP to Avro block boundaries, so pad rows to
                # the next chunk_rows multiple — a handful of stable shape
                # buckets instead of one XLA recompile per distinct chunk.
                n_pad = -(-chunk.n_rows // args.chunk_rows) * args.chunk_rows
                bundle = chunk.to_bundle(
                    pad_rows_to=n_pad, pad_nnz_to=k_targets
                )
                scores = np.asarray(transformer.transform(bundle))
                scores = scores[: chunk.n_rows]
                # bundle.uids/id_tags are already materialized by to_bundle;
                # slice them instead of re-gathering the dictionaries.
                writer.append(
                    scores,
                    uids=bundle.uids[: chunk.n_rows],
                    labels=chunk.labels,
                )
                if suite:
                    acc_scores.append(scores)
                    acc_labels.append(chunk.labels)
                    acc_weights.append(chunk.weights)
                    for col in group_cols:
                        acc_tags[col].append(
                            _encode_tags(col, bundle.id_tags[col][: chunk.n_rows])
                        )
                n_rows += chunk.n_rows
                logger.info("scored %d rows", n_rows)
        except Unsupported as e:
            if n_rows:
                # A schema dialect change mid-stream after chunks were
                # already written: restarting per-record would duplicate
                # scored rows. Fail loud instead.
                raise
            logger.info("streaming unsupported (%s); whole-dataset path", e)
            bundle = reader.read_per_record(
                args.data, dtype=_dt, require_labels=suite is not None
            )
            evaluation = None
            if suite:
                scores, evaluation = transformer.transform_and_evaluate(
                    bundle, suite
                )
            else:
                scores = transformer.transform(bundle)
            writer.append(
                np.asarray(scores), uids=bundle.uids, labels=bundle.labels
            )
            return bundle.n_rows, evaluation

    evaluation = None
    if suite and n_rows:
        from photon_tpu.estimators.game_transformer import (
            evaluate_scored_arrays,
        )

        evaluation = evaluate_scored_arrays(
            suite,
            np.concatenate(acc_scores),
            np.concatenate(acc_labels),
            np.concatenate(acc_weights),
            {},
            # Codes are already dense 0..n-1 per column (dictionary-encoded
            # per chunk above) — skip the full-dataset np.unique sort.
            factorized={
                col: (np.concatenate(parts), len(tag_codes[col]))
                for col, parts in acc_tags.items()
            },
        )
    return n_rows, evaluation


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
