"""Legacy single-GLM training driver: the pre-GAME pipeline with diagnostics.

Parity: reference ⟦photon-client/.../Driver.scala⟧ + ⟦.../diagnostics/⟧
(SURVEY.md §2.3 "Legacy GLM driver"): read training (+validation) Avro →
optional normalization → train one fixed-effect GLM per regularization
weight in the grid → validate and select → diagnostics on the selected model
(bootstrap coefficient CIs, Hosmer–Lemeshow calibration, feature importance)
→ save model + HTML fit report.

TPU-first: the per-λ fits reuse one jit-compiled solve (shapes/config are
identical across the grid, only ``reg_weight`` changes → one trace, many
executions); bootstrap replicates run as a single vmapped batch of solves.

Usage example:

    python -m photon_tpu.cli.glm_training_driver \
      --train-data data/train --validation-data data/val \
      --output-dir out --task LOGISTIC_REGRESSION \
      --regularization L2 --reg-weights 0.01 0.1 1 10 \
      --bootstrap-replicates 32
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

import numpy as np

from photon_tpu.cli.params import parse_feature_shard
from photon_tpu.data.normalization import NormalizationType, context_from_statistics
from photon_tpu.data.statistics import compute_feature_statistics
from photon_tpu.data.validators import DataValidationType, sanity_check_data
from photon_tpu.types import REAL_ACCELERATOR_BACKENDS
from photon_tpu.evaluation import EvaluationSuite
from photon_tpu.functions.problem import (
    GLMOptimizationProblem,
    VarianceComputationType,
)
from photon_tpu.index.index_map import MmapIndexMap, build_mmap_index
from photon_tpu.io.data_reader import (
    AvroDataReader,
    FeatureShardConfig,
    InputColumnNames,
    build_index_from_avro,
)
from photon_tpu.io.model_io import save_game_model
from photon_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_tpu.types import TaskType
from photon_tpu.utils import PhotonLogger, Timed

SHARD = "global"


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="glm-training-driver",
        description="Train a single fixed-effect GLM with diagnostics "
                    "(the reference's legacy pre-GAME Driver).",
    )
    p.add_argument("--train-data", nargs="+", required=True)
    p.add_argument("--validation-data", nargs="+", default=None)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", required=True, choices=[t.name for t in TaskType])
    p.add_argument("--feature-shard", default="global:features",
                   metavar="SHARD[:BAG+BAG][:no-intercept]",
                   help="single feature-shard spec (shard name must be "
                        f"'{SHARD}')")
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[o.name for o in OptimizerType])
    p.add_argument("--regularization", default="L2",
                   choices=[r.name for r in RegularizationType])
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--reg-weights", nargs="+", type=float, default=[1.0],
                   help="regularization-weight grid (reference's λ list)")
    p.add_argument("--max-iterations", type=int, default=80)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization", default="NONE",
                   choices=[n.name for n in NormalizationType])
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.name for v in DataValidationType])
    p.add_argument("--evaluators", nargs="+", default=None,
                   help="evaluator specs; first is primary; defaults per task")
    p.add_argument("--variance", default="SIMPLE",
                   choices=[v.name for v in VarianceComputationType],
                   help="coefficient variances saved with the model")
    p.add_argument("--index-dir", default=None)
    # Diagnostics (reference ⟦.../diagnostics/⟧):
    p.add_argument("--bootstrap-replicates", type=int, default=0,
                   help="0 disables bootstrap CIs")
    p.add_argument("--bootstrap-confidence", type=float, default=0.95)
    p.add_argument("--hl-bins", type=int, default=10,
                   help="Hosmer-Lemeshow bins (logistic task only)")
    p.add_argument("--no-report", action="store_true",
                   help="skip the HTML fit report")
    p.add_argument("--offset-column", default="offset")
    p.add_argument("--weight-column", default="weight")
    p.add_argument("--response-column", default="response")
    p.add_argument("--uid-column", default="uid")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"])
    p.add_argument("--devices", type=int, default=1,
                   help="out-of-core route only: stream row chunks sharded "
                        "over this many devices (0 = all visible, 1 = single "
                        "device; the device count must divide "
                        "--row-chunk-rows) — P1 data parallelism x "
                        "out-of-core")
    p.add_argument("--row-chunk-rows", type=int, default=-1,
                   help="out-of-core training: keep the ELL arrays "
                        "host-resident in row chunks of this size and stream "
                        "them through the accelerator per optimizer pass "
                        "(datasets beyond device memory; LBFGS+L2, "
                        "normalization/variance NONE). 0 = always in-core; "
                        "-1 = auto (accelerator backends route here when the "
                        "input file size exceeds "
                        "$PHOTON_DEVICE_DATA_BUDGET_GB, default 10)")
    from photon_tpu.cli.params import (
        add_backend_policy_flag,
        add_compilation_cache_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_backend_policy_flag(p)
    add_compilation_cache_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def _default_evaluators(task: TaskType) -> tuple[str, ...]:
    return {
        TaskType.LOGISTIC_REGRESSION: ("AUC", "LOGISTIC_LOSS"),
        TaskType.LINEAR_REGRESSION: ("RMSE",),
        TaskType.POISSON_REGRESSION: ("POISSON_LOSS",),
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: ("AUC",),
    }[task]


def _save_best(args, imap, shard_cfg, best, logger) -> None:
    """Persist the selected model as a standard single-coordinate GAME model
    plus its mmap index — shared by the in-core and out-of-core routes."""
    from photon_tpu.game.coordinates import FixedEffectModel
    from photon_tpu.game.descent import GameModel

    with Timed("save model", logger):
        gm = GameModel(models={
            "fixed": FixedEffectModel(model=best, feature_shard=SHARD)
        })
        save_game_model(
            os.path.join(args.output_dir, "best"), gm,
            {SHARD: imap}, {"fixed": SHARD}, {SHARD: shard_cfg},
        )
        idir = os.path.join(args.output_dir, "index", SHARD)
        if isinstance(imap, MmapIndexMap):
            if not os.path.exists(idir):
                import shutil

                shutil.copytree(imap.store_dir, idir)
        else:
            build_mmap_index(imap, idir)


def _ooc_unsupported_flag(args):
    """``(flag, wanted, got)`` for the first flag the out-of-core route
    cannot honor, else None. ONE source of truth shared by the auto-router
    (which must fall back in-core, never error, on a config that worked
    before OOC existed) and by ``_run_out_of_core`` (which fails loudly on
    an EXPLICIT --row-chunk-rows request it cannot honor)."""
    # Optimizer↔regularization pairing mirrors the in-core rules: smooth
    # L-BFGS takes L2; orthant-wise OWL-QN takes any L1 component (or pure
    # L2). TRON stays in-core (trust-region Hessian passes).
    ok_pairs = {
        ("LBFGS", "L2"), ("OWLQN", "L1"), ("OWLQN", "ELASTIC_NET"),
        ("OWLQN", "L2"),
    }
    if (args.optimizer, args.regularization) not in ok_pairs:
        if args.optimizer not in ("LBFGS", "OWLQN"):
            return "--optimizer", "LBFGS|OWLQN", args.optimizer
        return ("--regularization",
                "L2" if args.optimizer == "LBFGS" else "L1|ELASTIC_NET|L2",
                args.regularization)
    for flag, want, got in (
        ("--normalization", "NONE", args.normalization),
        ("--variance", "NONE", args.variance),
        ("--dtype", "float32", args.dtype),
    ):
        if got != want:
            return flag, want, got
    if args.bootstrap_replicates:
        return "--bootstrap-replicates", "0", str(args.bootstrap_replicates)
    return None


def _run_out_of_core(args, task, imap, shard_cfg, chunk_rows, logger) -> dict:
    """Out-of-core fixed-effect route (optim/out_of_core.py): host-resident
    row chunks streamed per pass — for datasets a single device's memory
    cannot hold. Supports L2/LBFGS (the config-5 scale shape) and
    L1/elastic-net/OWLQN (config 2 at scale); anything needing in-core
    data (normalization, variances, bootstrap, TRON) raises loudly instead
    of silently degrading."""
    import jax.numpy as jnp

    from photon_tpu.io.streaming import StreamingAvroReader
    from photon_tpu.optim.out_of_core import (
        ChunkedGLMData,
        run_out_of_core,
        scores_out_of_core,
    )

    bad = _ooc_unsupported_flag(args)
    if bad is not None:
        flag, want, got = bad
        raise ValueError(
            f"out-of-core training supports {flag}={want} only "
            f"(got {got}); pass --row-chunk-rows 0 to force in-core"
        )

    columns = InputColumnNames(
        uid=args.uid_column,
        response=args.response_column,
        offset=args.offset_column,
        weight=args.weight_column,
    )
    sreader = StreamingAvroReader(
        {SHARD: imap}, {SHARD: shard_cfg}, columns, (),
        chunk_rows=chunk_rows, capture_uids=False,
    )
    value_dtype = os.environ.get("PHOTON_VALUE_DTYPE")
    validation = DataValidationType[args.data_validation]
    # P1 x out-of-core: chunks stream row-sharded over a data mesh
    # (--devices N / 0 = all); the device count must divide chunk_rows.
    # Checked HERE, before hours of streaming decode — the solver's own
    # check would only fire after the whole dataset is in host RAM.
    from photon_tpu.cli.params import mesh_from_flags

    mesh = mesh_from_flags(getattr(args, "devices", 1))
    if mesh is not None:
        if chunk_rows % mesh.devices.size != 0:
            raise ValueError(
                f"--row-chunk-rows {chunk_rows} must be divisible by the "
                f"{mesh.devices.size}-device data mesh (--devices) for "
                "row-sharded streaming"
            )
        logger.info("out-of-core streaming over %d-device data mesh",
                    mesh.devices.size)

    # Same --data-validation contract as the in-core path, applied to each
    # ASSEMBLED fixed-shape chunk THE MOMENT it exists (fail fast: a NaN in
    # the first chunk of a 100M-row stream raises within seconds, not after
    # the whole dataset is decoded into host RAM). Chunks share one shape,
    # so the jitted violation counts compile once per ELL width (the width
    # can grow a few times mid-stream). Padding rows carry weight 0 / ghost
    # columns, the same convention the in-core bundle batch is validated
    # under. SAMPLE mode slices HOST-side so only the sampled rows cross to
    # the device; DISABLED transfers nothing.
    from photon_tpu.data.batch import LabeledBatch, SparseFeatures
    from photon_tpu.data.validators import SAMPLE_ROWS_DEFAULT

    def _validate_chunk(i, c, lab, off, wgt):
        if validation is DataValidationType.VALIDATE_SAMPLE:
            idx, val = c.idx[:SAMPLE_ROWS_DEFAULT], c.val[:SAMPLE_ROWS_DEFAULT]
            lab = lab[:SAMPLE_ROWS_DEFAULT]
            off = off[:SAMPLE_ROWS_DEFAULT]
            wgt = wgt[:SAMPLE_ROWS_DEFAULT]
        else:
            idx, val = c.idx, c.val
        sanity_check_data(
            LabeledBatch(
                features=SparseFeatures(idx=jnp.asarray(idx),
                                        val=jnp.asarray(val),
                                        dim=len(imap)),
                labels=lab,
                offsets=off,
                weights=wgt,
            ),
            task, validation,
        )

    on_chunk = (
        None if validation is DataValidationType.VALIDATE_DISABLED
        else _validate_chunk
    )
    with Timed("stream training data (host chunks, validated)", logger):
        data = ChunkedGLMData.from_stream(
            sreader.iter_chunks(args.train_data), SHARD, len(imap),
            chunk_rows=chunk_rows,
            value_dtype=jnp.dtype(value_dtype) if value_dtype else None,
            on_chunk=on_chunk,
        )
    logger.info(
        "out-of-core: %d rows in %d chunks, %.2f GB streamed per pass",
        data.n_rows, data.n_chunks, data.streamed_bytes_per_pass() / 1e9,
    )

    suite = EvaluationSuite.parse(
        list(args.evaluators or _default_evaluators(task))
    )
    reg = RegularizationContext(
        RegularizationType[args.regularization],
        elastic_net_alpha=args.elastic_net_alpha,
    )

    # Evaluation labels/weights: validation set in-core if given (it is
    # normally far smaller than train), else streamed train scores.
    val_batch = None
    if args.validation_data:
        reader = AvroDataReader({SHARD: imap}, {SHARD: shard_cfg},
                                columns=columns)
        with Timed("read validation data", logger):
            val_batch = reader.read(
                args.validation_data, capture_uids=False
            ).batch(SHARD)

    sweep, models, best_i = [], [], 0
    with Timed("regularization sweep (out-of-core)", logger):
        for i, lam in enumerate(args.reg_weights):
            problem = GLMOptimizationProblem(
                task=task,
                optimizer_type=OptimizerType[args.optimizer],
                optimizer_config=OptimizerConfig(
                    max_iterations=args.max_iterations,
                    tolerance=args.tolerance,
                ),
                regularization=reg,
                reg_weight=lam,
            )
            # Per-λ per-iteration checkpoint: a config-5-scale solve
            # outlives a flaky-tunnel recovery window, so a killed driver
            # rerun resumes at iteration k (the state fingerprint guards
            # against data/config drift; λ rides the filename).
            ck_dir = os.path.join(args.output_dir, "ooc_checkpoints")
            os.makedirs(ck_dir, exist_ok=True)
            model, result = run_out_of_core(
                problem, data,
                progress=lambda it, f, gn, p: logger.info(
                    "λ=%g iter %d: f=%.6g |g|=%.3g passes=%d", lam, it, f,
                    gn, p,
                ),
                checkpoint_path=os.path.join(ck_dir, f"lam_{lam:g}.npz"),
                mesh=mesh,
            )
            if val_batch is not None:
                scores = model.compute_score(
                    val_batch.features, val_batch.offsets
                )
                ev = suite.evaluate(scores, val_batch.labels,
                                    val_batch.weights)
            else:
                scores = scores_out_of_core(data, model.coefficients.means)
                ev = suite.evaluate(
                    scores, data.labels_np(), data.weights_np()
                )
            sweep.append({
                "reg_weight": lam,
                "iterations": int(result.iterations),
                "objective": float(result.value),
                "data_passes": int(result.data_passes),
                **{k: float(v) for k, v in ev.values.items()},
            })
            models.append(model)
            if i > 0 and suite.primary.better_than(
                ev.primary, sweep[best_i][suite.primary.name]
            ):
                best_i = i
            logger.info("λ=%g: %s", lam, sweep[-1])
    best, best_lam = models[best_i], args.reg_weights[best_i]
    logger.info("selected λ=%g (%s)", best_lam, suite.primary.name)

    _save_best(args, imap, shard_cfg, best, logger)

    summary = {
        "task": task.name,
        "mode": "out_of_core",
        "row_chunk_rows": chunk_rows,
        "n_rows": data.n_rows,
        "n_chunks": data.n_chunks,
        "streamed_gb_per_pass": round(
            data.streamed_bytes_per_pass() / 1e9, 3),
        "selected_reg_weight": best_lam,
        "sweep": sweep,
        "evaluation": sweep[best_i],
        "model_dir": os.path.join(args.output_dir, "best"),
    }
    with open(os.path.join(args.output_dir, "training-summary.json"),
              "w") as f:
        json.dump(summary, f, indent=2)
    return summary


def run(argv: Optional[Sequence[str]] = None) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import (
        enable_backend_guard,
        enable_compilation_cache,
        enable_telemetry,
        enable_trace,
        finish_telemetry,
        finish_trace,
    )

    # Fail-fast backend gate before anything can wedge in backend init
    # (PHOTON_BACKEND_INIT_TIMEOUT_S hard deadline; docs/robustness.md).
    enable_backend_guard(args)
    enable_compilation_cache(args.compilation_cache_dir)
    enable_telemetry(args, role="glm-training")
    enable_trace(args.trace_out)
    try:
        return _run(args)
    finally:
        finish_trace(args.trace_out)
        finish_telemetry(args)


def _run(args) -> dict:
    if args.dtype == "float64":
        import jax

        jax.config.update("jax_enable_x64", True)
    task = TaskType[args.task]
    os.makedirs(args.output_dir, exist_ok=True)
    with PhotonLogger(args.output_dir) as logger:
        shard_spec = parse_feature_shard(args.feature_shard)
        if shard_spec.shard != SHARD:
            raise ValueError(
                f"the single-GLM driver uses one shard named '{SHARD}', got "
                f"{shard_spec.shard!r}"
            )
        shard_cfg = FeatureShardConfig(
            feature_bags=shard_spec.feature_bags,
            add_intercept=shard_spec.add_intercept,
        )
        if args.index_dir:
            imap = MmapIndexMap(os.path.join(args.index_dir, SHARD))
        else:
            imap = build_index_from_avro(
                args.train_data,
                feature_bags=shard_cfg.feature_bags,
                add_intercept=shard_cfg.add_intercept,
            )
        logger.info("index: %d features", len(imap))

        ooc_rows = args.row_chunk_rows
        if ooc_rows < 0:
            import jax

            budget_gb = float(
                os.environ.get("PHOTON_DEVICE_DATA_BUDGET_GB", "10")
            )
            from photon_tpu.io.data_reader import _expand_paths

            total = sum(
                os.path.getsize(f) for f in _expand_paths(args.train_data)
            )
            # On-disk Avro bytes UNDERESTIMATE device footprint (deflate
            # blocks commonly shrink 3-5x; decoded ELL adds padding), so
            # the auto-route applies a conservative expansion factor.
            expand = float(
                os.environ.get("PHOTON_AVRO_EXPANSION_FACTOR", "4")
            )
            est = total * expand
            on_accel = jax.default_backend() in REAL_ACCELERATOR_BACKENDS
            ooc_rows = (1 << 20) if (
                on_accel and est > budget_gb * 1e9
            ) else 0
            bad = _ooc_unsupported_flag(args) if ooc_rows else None
            if bad is not None:
                # Auto-routing must never turn a formerly working in-core
                # run into a hard ValueError: any flag the OOC loop cannot
                # honor keeps the run in-core (the pre-OOC behavior — it may
                # OOM if the estimate was right, which is the same failure
                # the user had before) and says why.
                logger.warning(
                    "train data est. %.1f GB decoded exceeds device budget "
                    "%.0f GB but %s=%s requires the in-core path; staying "
                    "in-core (set %s=%s to enable out-of-core streaming — "
                    "forcing with --row-chunk-rows N also needs that flag)",
                    est / 1e9, budget_gb, bad[0], bad[2], bad[0], bad[1],
                )
                ooc_rows = 0
            if ooc_rows:
                logger.info(
                    "train data %.1f GB on disk (est. %.1f GB decoded) "
                    "exceeds device budget %.0f GB: out-of-core path "
                    "(chunk %d rows)",
                    total / 1e9, est / 1e9, budget_gb, ooc_rows,
                )
        if ooc_rows:
            return _run_out_of_core(args, task, imap, shard_cfg, ooc_rows,
                                    logger)

        reader = AvroDataReader(
            {SHARD: imap},
            {SHARD: shard_cfg},
            columns=InputColumnNames(
                uid=args.uid_column,
                response=args.response_column,
                offset=args.offset_column,
                weight=args.weight_column,
            ),
        )
        read_dtype = np.float64 if args.dtype == "float64" else np.float32
        with Timed("read training data", logger):
            # Training never reads the uid column (same memory contract as
            # the GAME training driver).
            train = reader.read(
                args.train_data, dtype=read_dtype, capture_uids=False
            )
        batch = train.batch(SHARD)
        sanity_check_data(batch, task, DataValidationType[args.data_validation])
        # No-op off-accelerator; on TPU the solves run the MXU-friendly
        # sparse layouts instead of the generic gather/scatter.
        batch = batch.with_accelerator_paths()
        val_batch = None
        if args.validation_data:
            with Timed("read validation data", logger):
                val_batch = reader.read(
                    args.validation_data, dtype=read_dtype,
                    capture_uids=False,
                ).batch(SHARD)

        import jax.numpy as jnp

        # One stats pass serves both the normalization context and the
        # feature-importance diagnostic.
        stats = compute_feature_statistics(batch)
        norm = None
        if NormalizationType[args.normalization] != NormalizationType.NONE:
            norm = context_from_statistics(
                stats, NormalizationType[args.normalization],
                imap.intercept_index,
            )

        suite = EvaluationSuite.parse(
            list(args.evaluators or _default_evaluators(task))
        )
        reg = RegularizationContext(
            RegularizationType[args.regularization],
            elastic_net_alpha=args.elastic_net_alpha,
        )
        opt_type = OptimizerType[args.optimizer]
        d = batch.features.dim
        w0 = jnp.zeros((d,), batch.labels.dtype)

        def make_problem(lam: float, variance: VarianceComputationType):
            return GLMOptimizationProblem(
                task=task,
                optimizer_type=opt_type,
                optimizer_config=OptimizerConfig(
                    max_iterations=args.max_iterations,
                    tolerance=args.tolerance,
                ),
                regularization=reg,
                reg_weight=lam,
                variance_type=variance,
            )

        eval_batch = val_batch if val_batch is not None else batch
        sweep, best_i = [], 0
        models = []
        # Sweep with variances OFF (reg_weight is a dynamic jit argument, so
        # the whole grid shares one compiled solve); the winner's variances
        # are computed once afterwards via a warm-started refit.
        with Timed("regularization sweep", logger):
            for i, lam in enumerate(args.reg_weights):
                model, result = make_problem(
                    lam, VarianceComputationType.NONE
                ).fit(batch, w0, normalization=norm)
                scores = model.compute_score(
                    eval_batch.features, eval_batch.offsets
                )
                ev = suite.evaluate(scores, eval_batch.labels, eval_batch.weights)
                sweep.append({
                    "reg_weight": lam,
                    "iterations": int(result.iterations),
                    "objective": float(result.value),
                    **{k: float(v) for k, v in ev.values.items()},
                })
                models.append(model)
                if suite.primary.better_than(
                    ev.primary, sweep[best_i][suite.primary.name]
                ) and i > 0:
                    best_i = i
                logger.info("λ=%g: %s", lam, sweep[-1])
        best = models[best_i]
        best_lam = args.reg_weights[best_i]
        logger.info("selected λ=%g (%s)", best_lam, suite.primary.name)
        variance_type = VarianceComputationType[args.variance]
        if variance_type != VarianceComputationType.NONE:
            with Timed("selected-model variances", logger):
                best, _ = make_problem(best_lam, variance_type).fit(
                    batch, best.coefficients.means, normalization=norm
                )

        # ---- diagnostics on the selected model (reference ⟦diagnostics/⟧)
        from photon_tpu.diagnostics import (
            bootstrap_coefficients,
            feature_importance,
            hosmer_lemeshow,
            write_fit_report,
        )

        boot = None
        if args.bootstrap_replicates > 0:
            with Timed("bootstrap CIs", logger):
                boot = bootstrap_coefficients(
                    make_problem(best_lam, VarianceComputationType.NONE),
                    batch, w0,
                    n_replicates=args.bootstrap_replicates,
                    confidence=args.bootstrap_confidence,
                    normalization=norm,
                )
        hl = None
        if task == TaskType.LOGISTIC_REGRESSION and args.hl_bins > 1:
            scores = best.compute_score(eval_batch.features, eval_batch.offsets)
            hl = hosmer_lemeshow(scores, eval_batch.labels, n_bins=args.hl_bins,
                                 weights=eval_batch.weights)
            logger.info("Hosmer-Lemeshow: stat=%.3f df=%d p=%.4f",
                        hl.statistic, hl.df, hl.p_value)
        imp = feature_importance(np.asarray(best.coefficients.means), stats)

        _save_best(args, imap, shard_cfg, best, logger)

        report_path = None
        if not args.no_report:
            names = [imap.get_feature(j) for j in range(len(imap))]
            report_path = write_fit_report(
                args.output_dir,
                task=task.name,
                feature_names=[f"{n}:{t}" if t else n for n, t in names],
                coefficients=np.asarray(best.coefficients.means),
                config_summary={
                    "optimizer": opt_type.name,
                    "regularization": reg.reg_type.name,
                    "selected_reg_weight": best_lam,
                    "normalization": args.normalization,
                    "dtype": args.dtype,
                    "n_rows": train.n_rows,
                    "n_features": d,
                },
                sweep_metrics=sweep,
                bootstrap=boot,
                hosmer_lemeshow=hl,
                importance=imp,
            )
            logger.info("fit report: %s", report_path)

        summary = {
            "task": task.name,
            "selected_reg_weight": best_lam,
            "sweep": sweep,
            "evaluation": sweep[best_i],
            "hosmer_lemeshow_p": None if hl is None else hl.p_value,
            "report": report_path,
            "model_dir": os.path.join(args.output_dir, "best"),
        }
        with open(os.path.join(args.output_dir, "training-summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
