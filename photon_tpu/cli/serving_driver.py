"""Online GAME scoring server driver (docs/serving.md).

The fourth driver: where the scoring driver reads a dataset and writes a
file, this one loads the same training-driver output directory and serves
single-row JSON requests at low latency:

    python -m photon_tpu.cli.serving_driver \\
        --model-dir out/best --port 8080 --output-dir serve_logs

    curl -s localhost:8080/score -d '{"features": [{"name": "g", \\
        "term": "0", "value": 1.2}], "entities": {"userId": "user3"}}'

Scores are identical to the batch scorer's (same index maps, same additive
kernel — tested parity), unseen entities fall back to fixed-effect-only,
and ``POST /admin/swap`` hot-swaps to a newly trained model directory
without dropping in-flight requests.
"""
from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from photon_tpu.serving import (
    MicroBatcher,
    ModelRegistry,
    ScoringServer,
    ServingConfig,
)
from photon_tpu.utils import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serving-driver",
        description="Serve a trained GAME model over HTTP (JSON rows).",
    )
    p.add_argument("--model-dir", required=True,
                   help="a 'best' or 'models/<i>' directory from the "
                        "training driver")
    p.add_argument("--index-dir", default=None,
                   help="per-shard index stores (default: <model-dir>/../index)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="0 binds an ephemeral port (logged at startup)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="micro-batch row cap (bucket shapes warm at startup)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="micro-batcher coalescing window")
    p.add_argument("--cache-entities", type=int, default=4096,
                   help="LRU device hot-set capacity per RE coordinate")
    p.add_argument("--max-row-nnz", type=int, default=128,
                   help="per-shard feature cap per request row (stable-shape "
                        "contract; over-cap rows get HTTP 400)")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission-queue bound; beyond it requests shed "
                        "with HTTP 503 + Retry-After (docs/robustness.md)")
    p.add_argument("--request-timeout", type=float, default=30.0,
                   help="per-request deadline in seconds, enforced inside "
                        "the batcher (expired rows never reach the kernel)")
    p.add_argument("--breaker-failures", type=int, default=5,
                   help="consecutive coefficient-store failures that open "
                        "the circuit breaker (0 disables); while open, RE "
                        "lookups degrade to fixed-effect-only scoring")
    p.add_argument("--breaker-cooldown", type=float, default=2.0,
                   help="seconds the breaker stays open before a probe")
    p.add_argument("--output-dir", default=None,
                   help="photon.log + serving-metrics.jsonl land here")
    # Replica mode (docs/serving.md §"Replication"): tail the durable
    # delta log instead of waiting for point-to-point /admin/patch pushes.
    p.add_argument("--delta-log", default=None,
                   help="durable delta log (JSONL) to tail as a serving "
                        "REPLICA: every logged delta applies exactly once "
                        "through the registry, the seq watermark + lag "
                        "ride /healthz, and a kill/rejoin resumes from "
                        "the per-replica cursor")
    p.add_argument("--replica-id", default=None,
                   help="stable replica identity for the cursor file, "
                        "journal rows, and metrics labels (default: "
                        "r<pid> — NOT restart-stable; set it explicitly "
                        "for rejoin-and-converge)")
    p.add_argument("--cursor-dir", default=None,
                   help="directory for the per-replica cursor (default: "
                        "--output-dir, else the delta log's directory)")
    p.add_argument("--catchup-lag", type=int, default=0,
                   help="replay backlog beyond which a rejoining replica "
                        "jumps to the log's latest full-snapshot marker "
                        "via prepare_standby/swap instead of replaying "
                        "(0 disables snapshot catch-up)")
    # Front-line mode (docs/serving.md §"Front line"): multi-process
    # serving box — N accelerator-free async workers share --port via
    # SO_REUSEPORT and feed THIS process (the single device owner) over
    # lock-free shared-memory rings carrying binary wire frames. The
    # in-process HTTP server stays up on an ephemeral port as the box's
    # admin plane (/admin/swap, /admin/patch, /metrics).
    p.add_argument("--workers", type=int, default=0,
                   help="front-end worker processes; 0 = classic "
                        "single-process threaded server")
    p.add_argument("--ipc", choices=["auto", "shm", "socket"],
                   default="auto",
                   help="worker<->scorer transport: lock-free shared-"
                        "memory rings or unix-socket fallback (auto "
                        "probes /dev/shm)")
    p.add_argument("--autotune", action="store_true",
                   help="histogram-autotuned micro-batching: continuously "
                        "re-choose (max_batch, max_wait_ms) from live "
                        "serve_stage_latency_seconds deltas, damped with "
                        "hysteresis + cooldown (docs/serving.md "
                        "§'Autotuned batching')")
    p.add_argument("--metrics-interval", type=float, default=60.0,
                   help="seconds between JSONL metrics snapshots")
    p.add_argument("--slo-config",
                   default=os.environ.get("PHOTON_SLO_CONFIG") or None,
                   help="JSON SLO rules (docs/observability.md §SLO) "
                        "judged at every metrics flush; violations bump "
                        "slo_violations_total and emit trace instants")
    from photon_tpu.cli.params import (
        add_backend_policy_flag,
        add_compilation_cache_flag,
        add_fault_plan_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_backend_policy_flag(p)
    add_compilation_cache_flag(p)
    add_fault_plan_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    from photon_tpu.cli.params import add_compile_store_flag

    add_compile_store_flag(p)
    return p


def build_server(args) -> tuple[ScoringServer, PhotonLogger]:
    """Registry (load + warm) → batcher → HTTP front-end, not yet serving."""
    from photon_tpu.cli.params import (
        enable_backend_guard,
        enable_compilation_cache,
        enable_compile_store,
        enable_fault_plan,
        enable_telemetry,
        enable_trace,
    )

    # Fail-fast backend gate: a serving box with a wedged accelerator must
    # refuse to start (strict) or come up on CPU with the swap stamped
    # (failover) within PHOTON_BACKEND_INIT_TIMEOUT_S — never hang the
    # deploy for 25 minutes inside model warmup's first device touch.
    enable_backend_guard(args)
    enable_compilation_cache(args.compilation_cache_dir)
    # Opt-in AOT compile store (docs/robustness.md §"Recovery time"):
    # warmup records every bucket shape, so a RESTARTED serving process
    # (or the kernel-breaker re-warmup after a device loss) loads its
    # whole compiled ladder from the persistent cache instead of paying
    # XLA during the deploy window. No output-dir default here — serving
    # boxes opt in with --compile-store / $PHOTON_COMPILE_STORE.
    if getattr(args, "compile_store", None):
        enable_compile_store(args)
    enable_fault_plan(args.fault_plan)
    # A delta-log tailer makes this process a REPLICA in the fleet
    # topology (docs/serving.md §"Replication") — the role rides every
    # trace anchor and telemetry shard name.
    role = "replica" if getattr(args, "delta_log", None) else "serving"
    telemetry_dir = enable_telemetry(args, role=role)
    enable_trace(args.trace_out)
    plogger = PhotonLogger(args.output_dir)
    logger = plogger.logger
    config = ServingConfig(
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        cache_entities=args.cache_entities,
        max_row_nnz=args.max_row_nnz,
        max_queue=args.max_queue,
        request_timeout_s=args.request_timeout,
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown,
    )
    from photon_tpu.utils import Timed

    with Timed("load + warm model", logger):
        registry = ModelRegistry(
            args.model_dir, config, index_dir=args.index_dir
        )
    batcher = MicroBatcher(
        max_batch=config.max_batch,
        max_wait_ms=config.max_wait_ms,
        max_queue=config.max_queue,
    )
    # JSONL metrics history lands in the output dir as before; without
    # one, a --telemetry-dir still captures it under the fleet shard
    # naming so the run report's anomaly scan has a series to read.
    if args.output_dir:
        metrics_path = os.path.join(args.output_dir,
                                    "serving-metrics.jsonl")
    elif telemetry_dir:
        metrics_path = os.path.join(
            telemetry_dir, f"metrics.{role}.{os.getpid()}.jsonl")
    else:
        metrics_path = None
    server = ScoringServer(
        registry,
        batcher,
        host=args.host,
        port=args.port,
        logger=logger,
        metrics_path=metrics_path,
        metrics_interval_s=args.metrics_interval,
        request_timeout_s=config.request_timeout_s,
        slo_config=args.slo_config,
    )
    if telemetry_dir:
        # Live fleet view: the flush loop re-exports this shard on the
        # metrics cadence (same path finish_telemetry finalizes at exit),
        # so the obs driver's /fleet aggregates this process while it
        # still serves.
        server.telemetry_shard_path = os.path.join(
            telemetry_dir, f"registry.{role}.{os.getpid()}.json")
    if getattr(args, "delta_log", None):
        from photon_tpu.replication import ReplicaTailer
        from photon_tpu.supervisor import RecoveryJournal

        journal = (
            RecoveryJournal(os.path.join(args.output_dir,
                                         "recovery.jsonl"))
            if args.output_dir else None
        )
        tailer = ReplicaTailer(
            registry,
            args.delta_log,
            replica_id=args.replica_id,
            cursor_dir=args.cursor_dir or args.output_dir or None,
            catchup_lag=args.catchup_lag,
            journal=journal,
            logger=logger,
            metrics=server.metrics,
        )
        # Converge to the log head BEFORE the first health check can read
        # a watermark: a rejoining replica that advertised itself while
        # still replaying its backlog would soak up traffic at stale
        # coefficients. (The follow thread starts with serving, in _run.)
        applied = tailer.run_once()
        server.attach_replication(tailer)
        snap = tailer.snapshot()
        if journal is not None:
            journal.record("replica_joined", replica=tailer.replica_id,
                           seq_watermark=snap["seq_watermark"],
                           applied_at_join=applied)
        logger.info(
            "replica %s joined: delta log %s, watermark %d "
            "(%d record(s) applied at boot, %d catch-up jump(s))",
            tailer.replica_id, args.delta_log, snap["seq_watermark"],
            applied, snap["catchups"],
        )
    v = registry.current
    logger.info(
        "serving model version %d (%s) on http://%s:%d  "
        "[coordinates: %s; max_batch=%d, wait=%.1fms, cache=%d]",
        v.version, v.model_dir, *server.address,
        ",".join(sorted(v.coordinates)), config.max_batch,
        config.max_wait_ms, config.cache_entities,
    )
    return server, plogger


def run(
    argv: Optional[Sequence[str]] = None, serve_forever: bool = True
) -> dict:
    """Build and (by default) serve until interrupted. ``serve_forever=
    False`` builds, warms, and tears down — the smoke/integration entry."""
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import finish_trace

    # finish_trace in a finally covering the BUILD too: a model load or
    # warmup failure is exactly the run whose timeline matters most.
    try:
        return _run(args, serve_forever)
    finally:
        finish_trace(args.trace_out)


def _build_frontline(args, server, public_port: int):
    """Assemble (not start) the multi-process front line around a built
    server: optional histogram autotuner + the worker supervisor."""
    from photon_tpu.serving.autotune import BatchAutotuner
    from photon_tpu.serving.frontline import FrontLine

    tuner = None
    if args.autotune:
        scorer = server.registry.current.scorer
        tuner = BatchAutotuner(
            server.batcher,
            server._stage_hist,
            ladder_max=scorer._max_batch_cap,
            # The cap moves with OOM downshifts and hot-swaps; resolve it
            # through the registry at every tick, never cache it.
            cap_fn=lambda: server.registry.current.scorer._max_batch_cap,
            logger=server.logger,
        )
        server.autotuner = tuner
    journal = None
    if args.output_dir:
        from photon_tpu.supervisor import RecoveryJournal

        journal = RecoveryJournal(
            os.path.join(args.output_dir, "recovery.jsonl"))
    if args.output_dir:
        runtime_dir = os.path.join(args.output_dir, "frontline")
    else:
        import tempfile

        runtime_dir = tempfile.mkdtemp(prefix="photon-frontline-")
    return FrontLine(
        server,
        workers=args.workers,
        host=args.host,
        port=public_port,
        runtime_dir=runtime_dir,
        transport=args.ipc,
        autotuner=tuner,
        telemetry_dir=getattr(args, "telemetry_dir", None),
        journal=journal,
        logger=server.logger,
    )


def _run(args, serve_forever: bool) -> dict:
    frontline_port = None
    if getattr(args, "workers", 0) > 0:
        from photon_tpu.serving.frontline import pick_port

        # Workers take the public port (SO_REUSEPORT); the in-process
        # HTTP server drops to an ephemeral port as the admin plane.
        frontline_port = args.port or pick_port(args.host)
        args.port = 0
    server, plogger = build_server(args)
    v = server.registry.current
    summary = {
        "address": list(server.address),
        "model_version": v.version,
        "model_dir": v.model_dir,
        "coordinates": sorted(v.coordinates),
    }
    from photon_tpu.cli.params import finish_telemetry

    if server.replication is not None:
        summary["replica_id"] = server.replication.replica_id
    fl = None
    if frontline_port is not None:
        fl = _build_frontline(args, server, frontline_port)
        summary["address"] = [args.host, frontline_port]
        summary["admin_address"] = list(server.address)
        summary["frontline"] = {
            "workers": args.workers,
            "transport": fl.transport,
            "runtime_dir": fl.runtime_dir,
            "autotune": bool(args.autotune),
        }
    if not serve_forever:
        server.shutdown()
        finish_telemetry(args, registries=(server.metrics,))
        plogger.close()
        return summary
    def _graceful(signum, frame):
        raise KeyboardInterrupt

    try:
        import signal

        # Production stops send SIGTERM; route it through the same graceful
        # path as Ctrl-C (drain batcher, flush metrics) instead of dying
        # with requests in flight. Main-thread only — embedded callers that
        # run() from a worker thread keep their process's handlers.
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:
        pass
    try:
        if server.replication is not None:
            server.replication.start()  # follow the log while serving
        if fl is not None:
            fl.start()
            server.logger.info(
                "front line: %d worker(s) on http://%s:%d (%s), admin "
                "plane on http://%s:%d%s",
                args.workers, args.host, frontline_port, fl.transport,
                *server.address,
                ", autotune on" if args.autotune else "")
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # Stop mutating the registry before the drain: a delta landing
        # mid-teardown has no one left to serve it.
        if server.replication is not None:
            server.replication.stop()
        # Workers first: they hold the public port and must stop taking
        # traffic before the batcher they feed goes away.
        if fl is not None:
            fl.stop()
        server.shutdown()
        # Registry shard AFTER shutdown: the final flush's counters are
        # exactly what the fleet report should aggregate.
        finish_telemetry(args, registries=(server.metrics,))
        plogger.close()
    return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
