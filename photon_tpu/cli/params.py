"""CLI parameter parsing shared by the GAME drivers.

Parity: reference ⟦photon-client/.../cli/game/GameDriver.scala,
ScoptGameTrainingParametersParser, ScoptGameScoringParametersParser⟧
(SURVEY.md §2.3 "Param parsing"): declarative flag → config bridging with
cross-validation, including the reference's per-coordinate configuration
mini-DSL.

Coordinate spec mini-DSL (one ``--coordinate`` flag per coordinate):

    <cid>:<k>=<v>,<k>=<v>,...

keys: ``type`` fixed|random|factored (required); ``shard`` feature shard id;
``re_type`` entity id column (random/factored, required); ``active_bound``
int; ``min_rows`` int; ``optimizer`` LBFGS|OWLQN|TRON; ``max_iter`` int;
``tol`` float; ``reg`` NONE|L1|L2|ELASTIC_NET; ``alpha`` elastic-net α;
``reg_weights`` '|'-separated floats (sweep, default 0); ``downsample`` rate;
``variance`` NONE|SIMPLE|FULL; ``incremental`` prior weight for incremental
training from --model-input-dir (requires it); ``latent``/``alternations``
(factored only) latent dimension and alternation count.

Example:
    --coordinate "fixed:type=fixed,shard=global,optimizer=LBFGS,reg=L2,reg_weights=0.1|1|10"
    --coordinate "perUser:type=random,re_type=userId,shard=user,reg=L2,reg_weights=1"
"""
from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Optional, Sequence

# The estimator/optimizer config types reach jax-backed kernels on import.
# They are needed only by the coordinate mini-DSL parsers, so they load
# lazily inside those functions — the accelerator-free drivers (router,
# control) import this module for flag helpers and must stay jax-free.
if TYPE_CHECKING:  # pragma: no cover - typing only
    from photon_tpu.estimators.config import (
        CoordinateDataConfig,
        GLMOptimizationConfiguration,
    )


@dataclasses.dataclass(frozen=True)
class CoordinateSpec:
    """One parsed ``--coordinate`` flag."""

    cid: str
    data: CoordinateDataConfig
    optimization: GLMOptimizationConfiguration
    reg_weights: tuple[float, ...]


_BOOL = {"true": True, "false": False}


def _parse_bool(cid: str, key: str, raw: str) -> bool:
    """Strict DSL booleans: silent False on a typo would quietly disable the
    scale knob and OOM at exactly the scale it exists for."""
    low = raw.strip().lower()
    if low in ("1", "true", "yes"):
        return True
    if low in ("0", "false", "no"):
        return False
    raise ValueError(
        f"coordinate {cid!r}: {key} must be one of 1/0/true/false/yes/no, "
        f"got {raw!r}"
    )


def parse_coordinate_spec(spec: str) -> CoordinateSpec:
    from photon_tpu.estimators.config import (
        FactoredRandomEffectDataConfig,
        FixedEffectDataConfig,
        GLMOptimizationConfiguration,
        RandomEffectDataConfig,
    )
    from photon_tpu.functions.problem import VarianceComputationType
    from photon_tpu.optim import OptimizerType
    from photon_tpu.optim.regularization import (
        RegularizationContext,
        RegularizationType,
        elastic_net_context,
    )

    cid, sep, body = spec.partition(":")
    cid = cid.strip()
    if not sep or not cid:
        raise ValueError(
            f"coordinate spec must be '<cid>:k=v,...', got {spec!r}"
        )
    kv: dict[str, str] = {}
    for item in body.split(","):
        item = item.strip()
        if not item:
            continue
        k, sep, v = item.partition("=")
        if not sep:
            raise ValueError(f"coordinate {cid!r}: bad item {item!r} (need k=v)")
        kv[k.strip()] = v.strip()

    known = {
        "type", "shard", "re_type", "active_bound", "min_rows", "max_features", "optimizer",
        "max_iter", "tol", "reg", "alpha", "reg_weights", "downsample",
        "variance", "incremental", "latent", "alternations",
        "max_bucket_entities", "host_resident",
    }
    unknown = set(kv) - known
    if unknown:
        raise ValueError(f"coordinate {cid!r}: unknown keys {sorted(unknown)}")

    ctype = kv.get("type")
    if ctype not in ("fixed", "random", "factored"):
        raise ValueError(
            f"coordinate {cid!r}: type must be 'fixed', 'random' or "
            f"'factored', got {ctype!r}"
        )
    shard = kv.get("shard", "global")
    if ctype == "fixed":
        for k in ("re_type", "active_bound", "min_rows", "max_features",
                  "latent", "alternations", "max_bucket_entities",
                  "host_resident"):
            if k in kv:
                raise ValueError(f"coordinate {cid!r}: {k} is random-effect only")
        data: CoordinateDataConfig = FixedEffectDataConfig(feature_shard=shard)
    else:
        if "re_type" not in kv:
            raise ValueError(f"coordinate {cid!r}: random effects need re_type")
        re_kwargs = dict(
            re_type=kv["re_type"],
            feature_shard=shard,
            active_bound=int(kv["active_bound"]) if "active_bound" in kv else None,
            min_entity_rows=int(kv.get("min_rows", 1)),
            max_features_per_entity=(
                int(kv["max_features"]) if "max_features" in kv else None
            ),
            max_bucket_entities=(
                int(kv["max_bucket_entities"])
                if "max_bucket_entities" in kv else None
            ),
            host_resident=_parse_bool(cid, "host_resident",
                                      kv.get("host_resident", "0")),
        )
        if ctype == "factored":
            data = FactoredRandomEffectDataConfig(
                latent_dim=int(kv.get("latent", 8)),
                n_alternations=int(kv.get("alternations", 2)),
                **re_kwargs,
            )
        else:
            if "latent" in kv or "alternations" in kv:
                raise ValueError(
                    f"coordinate {cid!r}: latent/alternations need type=factored"
                )
            data = RandomEffectDataConfig(**re_kwargs)

    reg_type = RegularizationType(kv.get("reg", "NONE").upper())
    if reg_type == RegularizationType.ELASTIC_NET:
        reg_ctx = elastic_net_context(float(kv.get("alpha", 0.5)))
    else:
        reg_ctx = RegularizationContext(reg_type)

    opt = GLMOptimizationConfiguration(
        optimizer_type=OptimizerType(kv.get("optimizer", "LBFGS").upper()),
        max_iterations=int(kv.get("max_iter", 80)),
        tolerance=float(kv.get("tol", 1e-7)),
        regularization=reg_ctx,
        down_sampling_rate=float(kv.get("downsample", 1.0)),
        variance_type=VarianceComputationType(kv.get("variance", "NONE").upper()),
        incremental_weight=float(kv.get("incremental", 0.0)),
    )
    weights = tuple(
        float(w) for w in kv.get("reg_weights", "0").split("|") if w != ""
    )
    if not weights:
        weights = (0.0,)
    return CoordinateSpec(cid=cid, data=data, optimization=opt, reg_weights=weights)


def parse_coordinates(specs: Sequence[str]) -> list[CoordinateSpec]:
    out = [parse_coordinate_spec(s) for s in specs]
    seen = set()
    for c in out:
        if c.cid in seen:
            raise ValueError(f"duplicate coordinate id {c.cid!r}")
        seen.add(c.cid)
    return out


def configs_from_specs(specs: Sequence[CoordinateSpec]):
    """(data configs by cid, optimization-config sweep) from parsed specs —
    the reference's Seq[GameOptimizationConfiguration] expansion."""
    from photon_tpu.estimators.config import reg_weight_sweep

    data_configs = {c.cid: c.data for c in specs}
    base = {c.cid: c.optimization.with_reg_weight(c.reg_weights[0]) for c in specs}
    sweep_axes = {
        c.cid: list(c.reg_weights) for c in specs if len(c.reg_weights) > 1
    }
    configs = reg_weight_sweep(base, sweep_axes) if sweep_axes else [base]
    return data_configs, configs


@dataclasses.dataclass(frozen=True)
class FeatureShardSpec:
    """One parsed ``--feature-shard`` flag: ``<shard>:<bag>[+<bag>...][:no-intercept]``."""

    shard: str
    feature_bags: tuple[str, ...]
    add_intercept: bool


def parse_feature_shard(spec: str) -> FeatureShardSpec:
    parts = spec.split(":")
    if not (1 <= len(parts) <= 3) or not parts[0]:
        raise ValueError(
            f"feature shard spec must be '<shard>[:<bag>+<bag>][:no-intercept]', got {spec!r}"
        )
    shard = parts[0]
    bags = tuple((parts[1] if len(parts) > 1 and parts[1] else "features").split("+"))
    add_intercept = True
    if len(parts) == 3:
        if parts[2] != "no-intercept":
            raise ValueError(f"feature shard {shard!r}: expected 'no-intercept', got {parts[2]!r}")
        add_intercept = False
    return FeatureShardSpec(shard, bags, add_intercept)


def mesh_from_flags(n_devices: int, mesh_spec=None):
    """Shared --devices/--mesh handling for the drivers: 0 = all visible
    devices, 1 = no mesh (None), N = data-axis mesh over the first N;
    ``mesh_spec`` ("data=4,model=2") builds an explicit multi-axis mesh.
    Negative counts and over-subscription fail loud."""
    import jax

    from photon_tpu.parallel.mesh import DATA_AXIS, make_mesh

    avail = len(jax.devices())
    if mesh_spec:
        axes = {}
        for item in mesh_spec.split(","):
            name, sep, size = item.partition("=")
            if not sep:
                raise ValueError(f"--mesh items must be axis=size, got {item!r}")
            axes[name.strip()] = int(size)
        if DATA_AXIS not in axes:
            raise ValueError(
                f"--mesh must include the '{DATA_AXIS}' axis (got {sorted(axes)})"
            )
        total = 1
        for s in axes.values():
            total *= s
        if total > avail:
            raise ValueError(f"--mesh needs {total} devices, have {avail}")
        return make_mesh(axes, devices=jax.devices()[:total])
    if n_devices < 0:
        raise ValueError(f"--devices must be >= 0, got {n_devices}")
    n = avail if n_devices == 0 else n_devices
    if n > avail:
        raise ValueError(f"--devices {n} > {avail} visible devices")
    if n <= 1:
        return None
    return make_mesh({DATA_AXIS: n}, devices=jax.devices()[:n])


def add_compilation_cache_flag(parser) -> None:
    """Shared --compilation-cache-dir flag (default: $PHOTON_XLA_CACHE_DIR)."""
    import os

    parser.add_argument(
        "--compilation-cache-dir",
        default=os.environ.get("PHOTON_XLA_CACHE_DIR") or None,
        help="persistent XLA compilation cache directory: compiled programs "
             "survive process restarts (supervisor relaunches, repeated "
             "driver runs), so a 20-40s accelerator compile is paid once "
             "per program shape, not once per process "
             "(default: $PHOTON_XLA_CACHE_DIR)")


def enable_compilation_cache(path) -> None:
    """Turn on jax's persistent compilation cache at ``path`` (no-op if
    falsy). Must run before the first jit compilation — jax only consults
    the cache dir at compile time, so everything compiled BEFORE this call
    is silently uncached and will recompile on the next restart. A late
    call used to be a silent no-op for those programs; now it is detected
    (any watched kernel already traced in this process) and warned LOUDLY,
    because a driver that reorders its init quietly loses exactly the
    warm-restart behavior the recovery stack depends on
    (docs/robustness.md §"Recovery time")."""
    if not path:
        return
    import logging
    import os

    import jax

    from photon_tpu.runtime.compile_store import process_has_compiled

    if process_has_compiled():
        logging.getLogger("photon_tpu.cli").warning(
            "enable_compilation_cache(%r) called AFTER this process already "
            "compiled kernels: programs compiled before this point were NOT "
            "persisted and will recompile from scratch on the next restart "
            "(the cache handle is re-initialized now, so later compiles DO "
            "persist). Call it (or enable_compile_store) before the first "
            "jit dispatch — typically first thing in the driver, before "
            "data loading touches any jitted code.", path,
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("PHOTON_XLA_CACHE_MIN_SECS", "1.0")),
    )
    # A late enable used to be a TOTAL silent no-op: jax memoizes the
    # cache handle at the process's first compile (watched or not — even a
    # stray jnp.zeros counts), so setting the dir afterwards persisted
    # nothing, ever. Resetting the handle unconditionally makes the call
    # effective from here on (the warning above still marks pre-call
    # compiles as lost).
    from photon_tpu.runtime.compile_store import _reset_jax_cache_handle

    _reset_jax_cache_handle()


def add_compile_store_flag(parser) -> None:
    """Shared --compile-store flag (default: $PHOTON_COMPILE_STORE, else
    <output-dir>/compile-store): the AOT compile-artifact store that makes
    restarts and device-loss recoveries zero-recompile
    (runtime/compile_store.py; docs/robustness.md §"Recovery time")."""
    import os

    parser.add_argument(
        "--compile-store",
        default=os.environ.get("PHOTON_COMPILE_STORE") or None,
        help="AOT compile-artifact store directory: compiled-kernel "
             "signatures are recorded into a manifest and the supervisor / "
             "device-loss recovery pre-warms them from the persistent "
             "compilation cache instead of re-paying XLA "
             "(default: $PHOTON_COMPILE_STORE, else "
             "<output-dir>/compile-store; 'off' disables)")


def enable_compile_store(args, output_dir=None):
    """Activate the AOT compile store process-wide (``--compile-store off``
    disables). Defaults to ``<output-dir>/compile-store`` so supervised
    restarts and checkpoint resumes get zero-recompile behavior out of the
    box; when the driver wired no ``--compilation-cache-dir``, the store
    supplies the persistent-cache layer itself (see
    runtime/compile_store.configure). Returns the store or None."""
    import logging

    from photon_tpu.runtime import compile_store

    path = getattr(args, "compile_store", None)
    if path in ("off", "0", "none"):
        # Pin the opt-out: a fleet-wide $PHOTON_COMPILE_STORE must not
        # lazily re-activate behind the operator's explicit 'off'.
        compile_store.disable()
        return None
    if path is None and output_dir:
        import os

        path = os.path.join(output_dir, "compile-store")
    if not path:
        return None
    store = compile_store.configure(path)
    logging.getLogger("photon_tpu.cli").info(
        "AOT compile store: %s (%d recorded signature(s))",
        store.root, len(store.entries()))
    return store


def add_trace_flag(parser) -> None:
    """Shared --trace-out flag (default: $PHOTON_TRACE_OUT): write the
    run's spans — ingest blocks, coordinate steps, optimizer solves, the
    serving path, injected faults — as Chrome trace-event JSON, loadable
    in Perfetto (docs/observability.md)."""
    import os

    parser.add_argument(
        "--trace-out",
        default=os.environ.get("PHOTON_TRACE_OUT") or None,
        help="write an end-to-end Chrome trace-event JSON timeline of this "
             "run to this file (open in https://ui.perfetto.dev; "
             "docs/observability.md; default: $PHOTON_TRACE_OUT)")


def enable_trace(path) -> None:
    """Install the process-wide trace collector (no-op if falsy); pair
    with :func:`finish_trace` in a ``finally``."""
    if not path:
        return
    from photon_tpu.obs import start_tracing

    start_tracing()


def finish_trace(path) -> None:
    """Write and uninstall the collector installed by :func:`enable_trace`
    (no-op if falsy). Runs in the driver's ``finally`` so a failed run
    still leaves a timeline — failures are when the trace matters most."""
    if not path:
        return
    import logging

    from photon_tpu.obs import stop_tracing

    col = stop_tracing(path)
    if col is not None:
        logging.getLogger("photon_tpu.obs").info(
            "trace written: %s (%d events%s)", path, len(col.events),
            f", {col.dropped} dropped" if col.dropped else "",
        )


def add_telemetry_flag(parser) -> None:
    """Shared --telemetry-dir flag (default: $PHOTON_TELEMETRY_DIR): the
    fleet-observability convention (docs/observability.md §"Fleet view").
    Every cooperating process of one run points here; each writes its
    trace shard (``trace.<role>.<pid>.json``) and metrics-registry shard
    (``registry.<role>.<pid>.json``) into the shared directory, and
    ``python -m photon_tpu.obs.analysis report <dir>`` fuses them into
    one merged timeline + run report."""
    import os

    parser.add_argument(
        "--telemetry-dir",
        default=os.environ.get("PHOTON_TELEMETRY_DIR") or None,
        help="shared fleet-telemetry directory: this process writes its "
             "trace shard and metrics-registry shard here under the "
             "fleet naming convention, mergeable across processes by "
             "`python -m photon_tpu.obs.analysis report` "
             "(docs/observability.md §'Fleet view'; default: "
             "$PHOTON_TELEMETRY_DIR)")


def enable_telemetry(args, role: str):
    """Install the fleet-telemetry convention for this process: stamp its
    ROLE (carried by every trace anchor, whether or not a telemetry dir
    is set), and under ``--telemetry-dir`` default ``--trace-out`` into
    the shard layout so the trace lands where the aggregator looks.
    Returns the telemetry dir (or None). Call BEFORE enable_trace — the
    anchor is stamped at collector install."""
    import os

    from photon_tpu.obs import trace

    trace.set_process_role(role)
    d = getattr(args, "telemetry_dir", None)
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    if getattr(args, "trace_out", None) is None:
        args.trace_out = os.path.join(
            d, f"trace.{role}.{os.getpid()}.json")
    return d


def finish_telemetry(args, registries=()) -> None:
    """Export this process's metrics-registry shard into the telemetry
    dir (no-op without ``--telemetry-dir``). Runs in the driver's
    ``finally`` — a failed run's counters are exactly the ones the run
    report needs. Best-effort by contract: telemetry is evidence, never
    a new failure mode."""
    d = getattr(args, "telemetry_dir", None)
    if not d:
        return
    import logging
    import os

    from photon_tpu.obs import fleet, trace

    path = os.path.join(
        d, f"registry.{trace.process_role()}.{os.getpid()}.json")
    try:
        fleet.write_registry_shard(path, registries=list(registries))
    except Exception as e:  # noqa: BLE001 - evidence, never a failure mode
        logging.getLogger("photon_tpu.obs").warning(
            "registry shard export failed (%s): %s", path, e)


def add_re_routing_flags(parser) -> None:
    """Shared random-effect solver-routing flags (docs/scaling.md §"Solver
    routing"): ``--re-routing`` picks between the deterministic static gate
    ladder and the measured cost-model router; ``--re-cost-table`` persists
    the calibration results alongside the model so a warm restart skips
    the race AND reproduces the original routing decisions (a re-raced
    timing winner could differ and break bit-identical resume)."""
    import os

    parser.add_argument(
        "--re-routing", choices=["static", "measured"],
        default=os.environ.get("PHOTON_RE_ROUTING") or "static",
        help="random-effect bucket solver routing: 'static' = deterministic "
             "eligibility gates (primal/dual Newton, chunked tiers, vmapped "
             "fallback); 'measured' = per-bucket-shape cost table seeded by "
             "a one-time calibration race on the first sweep "
             "(game/solver_routing.py; default: $PHOTON_RE_ROUTING or "
             "static)")
    parser.add_argument(
        "--re-cost-table",
        default=os.environ.get("PHOTON_RE_COST_TABLE") or None,
        help="JSON file for the measured-routing cost table (loaded at "
             "startup if present, saved after every calibration race); "
             "defaults to <output-dir>/solver_costs.json under "
             "--re-routing measured (default: $PHOTON_RE_COST_TABLE)")
    parser.add_argument(
        "--clear-caches-per-config", action="store_true",
        default=os.environ.get("PHOTON_CLEAR_CACHES_PER_CONFIG") == "1",
        help="drop jax's compiled-executable caches at every optimization-"
             "config (λ) boundary: bounds the mmap'd JIT code-page growth "
             "that otherwise creeps toward vm.max_map_count and segfaults "
             "multi-day runs (supervisor.MapCountWatchdog warns; this flag "
             "acts). Off by default — in-core sweeps reuse executables "
             "across λ values when shapes repeat")


def enable_re_routing(args, output_dir=None) -> None:
    """Install the routing flags process-wide (env is the contract the
    bucket solver reads — see game/solver_routing.py). Under measured
    routing with no explicit table path, the table persists alongside the
    model in ``output_dir``."""
    import logging
    import os

    os.environ["PHOTON_RE_ROUTING"] = args.re_routing
    table = args.re_cost_table
    if table is None and args.re_routing == "measured" and output_dir:
        table = os.path.join(output_dir, "solver_costs.json")
    if table:
        os.environ["PHOTON_RE_COST_TABLE"] = table
        logging.getLogger("photon_tpu.cli").info(
            "RE solver routing: %s (cost table: %s%s)", args.re_routing,
            table, ", resuming" if os.path.exists(table) else "",
        )
    if getattr(args, "clear_caches_per_config", False):
        os.environ["PHOTON_CLEAR_CACHES_PER_CONFIG"] = "1"


def add_backend_policy_flag(parser) -> None:
    """Shared --backend-policy flag (default: $PHOTON_BACKEND_POLICY or
    'strict'): what to do when the accelerator backend fails its health
    probe (docs/robustness.md §"Backend-failure resilience"). The probe
    runs subprocess-isolated under the PHOTON_BACKEND_INIT_TIMEOUT_S hard
    deadline (default 120 s), so no entrypoint can hang ~25 minutes inside
    a wedged backend init."""
    import os

    parser.add_argument(
        "--backend-policy", choices=["strict", "failover", "cpu-only"],
        default=os.environ.get("PHOTON_BACKEND_POLICY") or "strict",
        help="on a failed backend health probe: 'strict' = classified "
             "error + nonzero exit (never silently train on the wrong "
             "hardware); 'failover' = re-enter on CPU with the swap "
             "stamped into provenance (artifacts resolve to backend=cpu); "
             "'cpu-only' = pin the CPU backend, never touch the "
             "accelerator (default: $PHOTON_BACKEND_POLICY or strict)")


def add_distributed_flags(parser) -> None:
    """Shared --distributed-policy flag (default: $PHOTON_DISTRIBUTED_POLICY
    or 'strict'): what to do when multi-host bring-up
    (``jax.distributed.initialize``) fails — coordinator unreachable, rank
    mismatch, preempted peer (docs/scaling.md §"Multi-host mesh"). Either
    way the failure is classified, counted, and journaled
    (``distributed_init_failed``); the policy only decides whether the
    process dies or degrades to single-host."""
    import os

    parser.add_argument(
        "--distributed-policy", choices=["strict", "degrade"],
        default=os.environ.get("PHOTON_DISTRIBUTED_POLICY") or "strict",
        help="on failed multi-host bring-up: 'strict' = classified error + "
             "exit 2 (a silent 1/N-sized mesh must never masquerade as the "
             "pod); 'degrade' = journal the failure and continue "
             "single-host (default: $PHOTON_DISTRIBUTED_POLICY or strict)")


def enable_backend_guard(args, logger=None) -> dict:
    """Enforce --backend-policy before any in-process backend init. A
    probe that already passed in this process is not repeated (driver
    re-entries and test suites stay fast); a failed probe under 'strict'
    raises BackendUnusable, which the console entry surfaces as a
    classified one-line error and a nonzero exit."""
    import logging

    from photon_tpu.runtime.backend_guard import ensure_backend

    return ensure_backend(
        policy=getattr(args, "backend_policy", "strict"),
        logger=logger or logging.getLogger("photon_tpu.runtime"),
    )


def console_main(run_fn) -> None:
    """Console-entry wrapper shared by the drivers: a failed backend
    health probe under --backend-policy strict exits with ONE classified
    line and status 2 — the operator (and the scheduler's log scraper)
    gets `fatal [init_unavailable]: ...`, not a 40-frame traceback ending
    in a jaxlib internal."""
    import sys

    from photon_tpu.runtime.backend_guard import BackendUnusable

    try:
        run_fn()
    except BackendUnusable as e:
        print(f"fatal [{e.cause}]: {e.reason}", file=sys.stderr)
        raise SystemExit(2) from None


def add_fault_plan_flag(parser) -> None:
    """Shared --fault-plan flag (default: $PHOTON_FAULT_PLAN): run the
    driver under a deterministic fault-injection plan for chaos drills
    (docs/robustness.md). Never set in production."""
    import os

    parser.add_argument(
        "--fault-plan",
        default=os.environ.get("PHOTON_FAULT_PLAN") or None,
        help="JSON FaultPlan file (photon_tpu.faults): inject seeded "
             "faults — IO errors, preemptions, store latency — at the "
             "framework's hook points to rehearse recovery paths "
             "(default: $PHOTON_FAULT_PLAN)")


def enable_fault_plan(path) -> None:
    """Install the plan file process-wide (no-op if falsy)."""
    if not path:
        return
    import logging

    from photon_tpu.faults import install_from_file

    install_from_file(path)
    logging.getLogger("photon_tpu.faults").warning(
        "FAULT INJECTION ACTIVE: plan %s (chaos drill — not production)",
        path,
    )
