"""Staleness-aware routing front door for serving replicas
(docs/serving.md §"Replication").

The seventh driver: where the serving driver answers ``/score`` itself,
this one fronts N of them — health-checking each replica's ``/healthz``
(status, degradation reasons, delta-log seq watermark), weighting traffic
toward the freshest healthy replicas, draining degraded or
memory-pressured ones, and retrying idempotent reads on a second replica
when a connection fails mid-request:

    python -m photon_tpu.cli.router_driver \\
        --replica http://127.0.0.1:8081 --replica http://127.0.0.1:8082 \\
        --port 8080 --output-dir router_logs

Deliberately accelerator-free: the router never imports jax and needs no
backend guard — it must keep routing while every replica behind it is
busy recompiling or recovering.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

from photon_tpu.utils import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="router-driver",
        description="Route /score traffic across serving replicas with "
                    "staleness- and pressure-aware weighting.",
    )
    p.add_argument("--replica", action="append", default=None,
                   metavar="URL", dest="replicas",
                   help="replica base URL (repeatable; at least one "
                        "required), e.g. http://127.0.0.1:8081")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="0 binds an ephemeral port (logged at startup)")
    p.add_argument("--health-interval", type=float, default=1.0,
                   help="seconds between /healthz sweeps across replicas")
    p.add_argument("--health-timeout", type=float, default=2.0,
                   help="per-replica /healthz timeout; a miss marks the "
                        "replica unreachable until the next sweep")
    p.add_argument("--staleness-penalty", type=float, default=0.25,
                   help="weight divisor per seq of delta-log lag behind "
                        "the freshest replica (0 = ignore staleness)")
    p.add_argument("--retries", type=int, default=1,
                   help="idempotent-read retries on a DIFFERENT replica "
                        "after a connection failure or 503 shed")
    p.add_argument("--request-timeout", type=float, default=30.0,
                   help="per-upstream-request deadline in seconds")
    p.add_argument("--seed", type=int, default=None,
                   help="pin the weighted-choice random stream "
                        "(deterministic routing for tests)")
    p.add_argument("--output-dir", default=None,
                   help="photon.log lands here")
    from photon_tpu.cli.params import (
        add_fault_plan_flag,
        add_telemetry_flag,
        add_trace_flag,
    )

    add_fault_plan_flag(p)
    add_telemetry_flag(p)
    add_trace_flag(p)
    return p


def run(argv: Optional[Sequence[str]] = None,
        serve_forever: bool = True) -> dict:
    args = build_arg_parser().parse_args(argv)
    from photon_tpu.cli.params import finish_trace

    try:
        return _run(args, serve_forever)
    finally:
        finish_trace(args.trace_out)


def _run(args, serve_forever: bool) -> dict:
    from photon_tpu.cli.params import (
        enable_fault_plan,
        enable_telemetry,
        enable_trace,
        finish_telemetry,
    )
    from photon_tpu.replication import RouterServer

    if not args.replicas:
        raise SystemExit("router-driver: at least one --replica required")
    enable_fault_plan(args.fault_plan)
    enable_telemetry(args, role="router")
    enable_trace(args.trace_out)
    plogger = PhotonLogger(args.output_dir)
    logger = plogger.logger
    router = RouterServer(
        args.replicas,
        host=args.host,
        port=args.port,
        health_interval_s=args.health_interval,
        health_timeout_s=args.health_timeout,
        staleness_penalty=args.staleness_penalty,
        retries=args.retries,
        timeout_s=args.request_timeout,
        logger=logger,
        seed=args.seed,
    )
    # One synchronous sweep before announcing ourselves: an immediate
    # client sees real routability, not "no replica available" while the
    # background health loop warms up.
    router.check_replicas()
    summary = {
        "address": list(router.address),
        "replicas": list(args.replicas),
        **{k: router.health_snapshot()[k]
           for k in ("status", "routable", "reachable")},
    }
    logger.info("router on http://%s:%d fronting %d replica(s): %s",
                *router.address, len(args.replicas), json.dumps(summary))
    if not serve_forever:
        router.shutdown()
        finish_telemetry(args, registries=(router.metrics,))
        plogger.close()
        return summary

    def _graceful(signum, frame):
        raise KeyboardInterrupt

    try:
        import signal

        # SIGTERM routes through the same graceful stop as Ctrl-C, same
        # contract as the serving driver. Main-thread only.
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:
        pass
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        router.shutdown()
        summary["requests"] = router.metrics_snapshot().get(
            "router_requests_total", {})
        finish_telemetry(args, registries=(router.metrics,))
        plogger.close()
    return summary


def main() -> None:  # pragma: no cover - console entry
    from photon_tpu.cli.params import console_main

    console_main(run)


if __name__ == "__main__":  # pragma: no cover
    main()
