"""HTML fit report for the legacy single-GLM pipeline.

Parity: reference ⟦photon-client/.../diagnostics/reporting/⟧ — the legacy
Driver renders an HTML summary (training config, per-λ metrics, coefficient
table with bootstrap CIs, calibration test, feature importance). Host-side,
stdlib only.
"""
from __future__ import annotations

import html
import json
import os
from typing import Mapping, Optional, Sequence

from photon_tpu.diagnostics.bootstrap import BootstrapResult
from photon_tpu.diagnostics.hosmer_lemeshow import HosmerLemeshowResult
from photon_tpu.diagnostics.importance import FeatureImportance

_STYLE = """
body { font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a1a; }
h1 { font-size: 1.4rem; } h2 { font-size: 1.1rem; margin-top: 1.6rem; }
table { border-collapse: collapse; margin: 0.6rem 0; }
th, td { border: 1px solid #ccc; padding: 0.25rem 0.6rem; text-align: right; }
th { background: #f2f2f2; } td.name { text-align: left; font-family: monospace; }
.note { color: #555; font-size: 0.85rem; }
"""


def _table(headers: Sequence[str], rows: Sequence[Sequence[object]]) -> str:
    h = "".join(f"<th>{html.escape(str(c))}</th>" for c in headers)
    body = []
    for row in rows:
        tds = []
        for i, c in enumerate(row):
            cls = ' class="name"' if i == 0 and isinstance(c, str) else ""
            text = f"{c:.6g}" if isinstance(c, float) else html.escape(str(c))
            tds.append(f"<td{cls}>{text}</td>")
        body.append("<tr>" + "".join(tds) + "</tr>")
    return f"<table><tr>{h}</tr>{''.join(body)}</table>"


def write_fit_report(
    output_dir: str,
    *,
    task: str,
    feature_names: Sequence[str],
    coefficients,
    config_summary: Mapping[str, object],
    sweep_metrics: Sequence[Mapping[str, object]] = (),
    bootstrap: Optional[BootstrapResult] = None,
    hosmer_lemeshow: Optional[HosmerLemeshowResult] = None,
    importance: Optional[FeatureImportance] = None,
    top_k: int = 25,
    filename: str = "fit-report.html",
) -> str:
    """Render the fit report; returns the written path. A machine-readable
    twin (``fit-report.json``) is written alongside it."""
    parts = [
        f"<html><head><meta charset='utf-8'><title>GLM fit report</title>"
        f"<style>{_STYLE}</style></head><body>",
        f"<h1>GLM fit report — {html.escape(task)}</h1>",
        "<h2>Configuration</h2>",
        _table(["parameter", "value"], sorted(config_summary.items())),
    ]
    if sweep_metrics:
        headers = sorted({k for m in sweep_metrics for k in m})
        parts += [
            "<h2>Regularization sweep</h2>",
            _table(headers, [[m.get(k, "") for k in headers] for m in sweep_metrics]),
        ]

    coefs = [float(c) for c in coefficients]
    order = importance.order if importance is not None else range(len(coefs))
    rows = []
    for rank, j in enumerate(order):
        if rank >= top_k:
            break
        j = int(j)
        row: list[object] = [feature_names[j], coefs[j]]
        if bootstrap is not None:
            row += [float(bootstrap.lower[j]), float(bootstrap.upper[j]),
                    float(bootstrap.std_error[j])]
        if importance is not None:
            row.append(float(importance.importance[rank]))
        rows.append(row)
    headers = ["feature", "coefficient"]
    if bootstrap is not None:
        ci = f"{bootstrap.confidence:.0%}"
        headers += [f"CI low ({ci})", f"CI high ({ci})", "std err"]
    if importance is not None:
        headers.append("importance")
    parts += [f"<h2>Top coefficients (by importance)</h2>", _table(headers, rows)]
    if bootstrap is not None:
        parts.append(
            f"<p class='note'>Bootstrap: {bootstrap.n_replicates} multinomial "
            f"replicates fit in one vmapped solve; "
            f"{int(bootstrap.converged.sum())}/{bootstrap.n_replicates} "
            "converged.</p>"
        )

    if hosmer_lemeshow is not None:
        hl = hosmer_lemeshow
        parts += [
            "<h2>Hosmer–Lemeshow calibration</h2>",
            _table(
                ["statistic", "df", "p-value"],
                [[hl.statistic, hl.df, hl.p_value]],
            ),
            _table(
                ["bin", "n", "observed positives", "expected positives"],
                [[g, float(hl.bin_count[g]), float(hl.observed_positives[g]),
                  float(hl.expected_positives[g])] for g in range(hl.n_bins)],
            ),
            "<p class='note'>Small p-values reject calibration "
            "(decile-of-risk bins).</p>",
        ]

    parts.append("</body></html>")
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, filename)
    with open(path, "w") as f:
        f.write("\n".join(parts))

    machine = {
        "task": task,
        "config": {k: str(v) for k, v in config_summary.items()},
        "sweep_metrics": [dict(m) for m in sweep_metrics],
        "hosmer_lemeshow": None if hosmer_lemeshow is None else {
            "statistic": hosmer_lemeshow.statistic,
            "df": hosmer_lemeshow.df,
            "p_value": hosmer_lemeshow.p_value,
        },
        "n_bootstrap_replicates": None if bootstrap is None else bootstrap.n_replicates,
    }
    with open(os.path.join(output_dir, "fit-report.json"), "w") as f:
        json.dump(machine, f, indent=2)
    return path
