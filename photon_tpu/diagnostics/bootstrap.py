"""Bootstrap confidence intervals for GLM coefficients.

Parity: reference ⟦photon-client/.../diagnostics/bootstrap/⟧ — the legacy
Driver trains models on bootstrap resamples of the training data and reports
percentile confidence intervals per coefficient.

TPU-first: resampling-with-replacement is expressed as multinomial *count
weights* (a resample that draws row i k times is the original batch with
``weights[i] *= k``), so all B replicate solves share one static batch and
run as a single ``vmap`` over the weight axis — one compiled program, B
parallel optimizer loops on device, instead of B sequential training jobs.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.functions.problem import GLMOptimizationProblem

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class BootstrapResult:
    """Percentile CIs from B replicate fits. All arrays are [D] except
    ``samples`` ([B, D]) and ``converged`` ([B] bool)."""

    lower: np.ndarray
    upper: np.ndarray
    mean: np.ndarray
    std_error: np.ndarray
    samples: np.ndarray
    converged: np.ndarray
    confidence: float

    @property
    def n_replicates(self) -> int:
        return self.samples.shape[0]


def bootstrap_coefficients(
    problem: GLMOptimizationProblem,
    batch: LabeledBatch,
    w0: Array,
    n_replicates: int = 32,
    confidence: float = 0.95,
    seed: int = 0,
    normalization=None,
) -> BootstrapResult:
    """Fit ``n_replicates`` multinomial-bootstrap resamples in one vmapped
    solve and return percentile confidence intervals.

    ``problem`` should have ``variance_type=NONE`` (replicate variances are
    never needed). ``normalization`` must match the context the reported
    model was trained with — otherwise the replicates minimize a different
    objective and the intervals describe the wrong estimator.
    """
    n = batch.n_rows
    rng = np.random.default_rng(seed)
    # Multinomial counts: each replicate draws n rows with replacement.
    counts = rng.multinomial(n, np.full(n, 1.0 / n), size=n_replicates)
    base_w = np.asarray(batch.weights)
    rep_weights = jnp.asarray(counts * base_w[None, :], dtype=base_w.dtype)

    def solve_one(wts: Array):
        rep = dataclasses.replace(batch, weights=wts)
        model, result = problem.run(rep, w0, normalization=normalization)
        return model.coefficients.means, result.converged_reason

    means, reasons = jax.jit(jax.vmap(solve_one))(rep_weights)
    samples = np.asarray(means)
    alpha = (1.0 - confidence) / 2.0
    lower, upper = np.quantile(samples, [alpha, 1.0 - alpha], axis=0)
    return BootstrapResult(
        lower=lower,
        upper=upper,
        mean=samples.mean(axis=0),
        std_error=samples.std(axis=0, ddof=1),
        samples=samples,
        # FUNCTION_VALUES_CONVERGED (2) / GRADIENT_CONVERGED (3); replicates
        # that merely hit the iteration cap are flagged not-converged.
        converged=np.asarray(reasons) >= 2,
        confidence=confidence,
    )
