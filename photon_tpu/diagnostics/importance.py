"""Feature importance for fitted GLMs.

Parity: reference ⟦photon-client/.../diagnostics/featureimportance/⟧ — the
legacy Driver ranks features by expected |impact| on the linear score and
reports the top of the list in its fit report.

Importance of feature j is |w_j| · std_j (coefficient magnitude scaled by the
feature's spread in the training data), the standardized-coefficient measure
the reference's importance diagnostic approximates; features the model never
saw (std 0) rank by |w_j| · |mean_j| so constant-but-used columns (e.g. the
intercept) still appear.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from photon_tpu.data.statistics import FeatureDataStatistics


@dataclasses.dataclass(frozen=True)
class FeatureImportance:
    """Ranked importance. All arrays are [D], sorted descending."""

    order: np.ndarray        # int indices into the coefficient vector
    importance: np.ndarray   # importance score, aligned with ``order``

    def top(self, k: int) -> list[tuple[int, float]]:
        k = min(k, len(self.order))
        return [(int(self.order[i]), float(self.importance[i])) for i in range(k)]


def feature_importance(
    coefficients: np.ndarray, stats: FeatureDataStatistics
) -> FeatureImportance:
    w = np.asarray(coefficients, np.float64)
    std = np.asarray(stats.std(), np.float64)
    mean = np.asarray(stats.mean, np.float64)
    score = np.abs(w) * np.where(std > 0, std, np.abs(mean))
    order = np.argsort(-score, kind="stable")
    return FeatureImportance(order=order, importance=score[order])
