"""Model diagnostics for the legacy single-GLM pipeline.

Parity: reference ⟦photon-client/.../diagnostics/⟧ (SURVEY.md §2.3 "Legacy
GLM driver": bootstrap confidence intervals, Hosmer–Lemeshow calibration,
feature importance, HTML fit report).

TPU-first: the bootstrap refits all B replicates in ONE vmapped solve (the
reference trains replicate models sequentially as Spark jobs); Hosmer–
Lemeshow bins and the chi-square statistic are computed on device.
"""
from photon_tpu.diagnostics.bootstrap import (
    BootstrapResult,
    bootstrap_coefficients,
)
from photon_tpu.diagnostics.hosmer_lemeshow import (
    HosmerLemeshowResult,
    hosmer_lemeshow,
)
from photon_tpu.diagnostics.importance import (
    FeatureImportance,
    feature_importance,
)
from photon_tpu.diagnostics.report import write_fit_report

__all__ = [
    "BootstrapResult",
    "bootstrap_coefficients",
    "HosmerLemeshowResult",
    "hosmer_lemeshow",
    "FeatureImportance",
    "feature_importance",
    "write_fit_report",
]
