"""Hosmer–Lemeshow goodness-of-fit test for logistic models.

Parity: reference ⟦photon-client/.../diagnostics/hl/⟧ — decile-of-risk
calibration test reported by the legacy Driver's fit report.

TPU-first: the decile binning is a sort-free ``searchsorted`` against
quantile edges and the per-bin observed/expected sums are ``segment_sum``s —
one jitted pass over the scores, no host loop.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class HosmerLemeshowResult:
    """Chi-square calibration test over probability bins.

    ``p_value`` is from the chi-square distribution with ``df`` degrees of
    freedom; small values reject "the model is well calibrated". Bin arrays
    are [G].
    """

    statistic: float
    df: int
    p_value: float
    bin_count: np.ndarray
    observed_positives: np.ndarray
    expected_positives: np.ndarray

    @property
    def n_bins(self) -> int:
        return self.bin_count.shape[0]


@partial(jax.jit, static_argnums=3)
def _hl_bins(scores: Array, labels: Array, weights: Array, n_bins: int):
    p = jax.nn.sigmoid(scores)
    qs = jnp.quantile(p, jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1])
    g = jnp.searchsorted(qs, p, side="right")
    w = weights.astype(p.dtype)
    count = jax.ops.segment_sum(w, g, num_segments=n_bins)
    obs = jax.ops.segment_sum(w * labels.astype(p.dtype), g, num_segments=n_bins)
    exp = jax.ops.segment_sum(w * p, g, num_segments=n_bins)
    return count, obs, exp


def hosmer_lemeshow(
    scores: Array, labels: Array, n_bins: int = 10, weights: Array | None = None
) -> HosmerLemeshowResult:
    """HL test from raw margins (pre-sigmoid scores) and 0/1 labels.

    Uses the standard statistic Σ_g (O_g−E_g)² / (E_g (1 − E_g/n_g)) over
    ``n_bins`` quantile bins of predicted probability, df = n_bins − 2.
    With ``weights``, bin totals are weighted sums (bin edges stay plain
    score deciles), matching the weighted metrics elsewhere in the suite.
    """
    scores = jnp.asarray(scores)
    w = jnp.ones_like(scores) if weights is None else jnp.asarray(weights)
    count, obs, exp = _hl_bins(scores, jnp.asarray(labels), w, n_bins)
    count = np.asarray(count, np.float64)
    obs = np.asarray(obs, np.float64)
    exp = np.asarray(exp, np.float64)
    keep = count > 0
    denom = exp * (1.0 - exp / np.maximum(count, 1.0))
    terms = np.where(keep & (denom > 1e-12), (obs - exp) ** 2 / np.maximum(denom, 1e-12), 0.0)
    stat = float(terms.sum())
    df = max(int(keep.sum()) - 2, 1)
    # p = 1 − chi2.cdf(stat, df) = Q(df/2, stat/2) (regularized upper gamma).
    from scipy.special import gammaincc  # scipy ships with the baked deps

    p_value = float(gammaincc(df / 2.0, stat / 2.0))
    return HosmerLemeshowResult(
        statistic=stat,
        df=df,
        p_value=p_value,
        bin_count=count,
        observed_positives=obs,
        expected_positives=exp,
    )
