"""Failure detection and elastic restart supervision.

Parity: the reference inherits ALL of its failure handling from the Spark
runtime (SURVEY.md §5.3): task retry, stage re-execution from RDD lineage,
speculative execution, executor-loss recompute. JAX has none of that — a lost
chip, a preempted host, or a failed collective kills the training process.
The rebuild's recovery model is checkpoint-restart (``checkpoint.py``
provides bit-identical resume) plus this module, which supplies the two
missing Spark-runtime equivalents:

* :func:`run_with_recovery` — the "task retry" analog. Runs a training
  attempt, classifies failures as retryable (device/runtime/IO errors,
  preemptions) or fatal (config bugs: ``ValueError``/``TypeError``, and
  user aborts), and restarts up to a budget with exponential backoff. Each
  attempt re-enters the driver pipeline, where ``--checkpoint-dir`` resume
  fast-forwards past completed coordinate steps — so unlike Spark's lineage
  recompute, no finished work is redone.

  Scope note (honest limits): in-process retry covers transient failures
  that leave the runtime usable — input IO errors, preemption signals
  delivered as exceptions, coordinator hiccups. A hard device loss can
  poison the XLA client for the whole process; for that case the driver
  exits nonzero after the restart budget and the outer scheduler's process
  restart (k8s/systemd restartPolicy) is the recovery path — the same
  division of labor as Spark (task retry in-process, executor relaunch by
  YARN). Both paths land in the same checkpoint resume.

* :class:`Heartbeat` — the "executor loss detection" analog for multi-host
  runs. Every process writes a heartbeat file into a shared directory (the
  checkpoint filesystem); :meth:`Heartbeat.check_peers` reports processes
  whose beat has gone stale. XLA collectives have no internal peer-failure
  timeout (Spark's netty RPC and NCCL both do), so without detection a
  surviving host blocks forever inside a psum whose peer died. The training
  driver checks peers between restart attempts and fails fast with the dead
  host list instead of hanging.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Optional, Sequence

__all__ = [
    "RestartPolicy",
    "AttemptFailure",
    "RestartsExhausted",
    "run_with_recovery",
    "Heartbeat",
    "PeerReport",
]


def _default_retryable() -> tuple:
    """Exception types that plausibly heal on a restart: runtime/IO errors
    (includes jaxlib's XlaRuntimeError, which subclasses RuntimeError)."""
    return (RuntimeError, OSError, ConnectionError)


# Config bugs and user aborts: retrying cannot help, fail immediately even
# though some (e.g. a ValueError raised through a RuntimeError subclass
# hierarchy) might otherwise match.
_FATAL = (ValueError, TypeError, AssertionError, KeyboardInterrupt)


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """How many times to restart and how to pace the attempts."""

    max_restarts: int = 3
    backoff_seconds: float = 1.0
    backoff_multiplier: float = 2.0
    retryable: tuple = dataclasses.field(default_factory=_default_retryable)

    def is_retryable(self, err: BaseException) -> bool:
        if isinstance(err, _FATAL):
            return False
        return isinstance(err, self.retryable)


@dataclasses.dataclass
class AttemptFailure:
    """One failed attempt, for the supervision log."""

    attempt: int
    error_type: str
    message: str
    seconds: float


class RestartsExhausted(RuntimeError):
    """Raised when every attempt in the budget failed; carries the history."""

    def __init__(self, failures: Sequence[AttemptFailure], last: BaseException):
        self.failures = list(failures)
        self.last = last
        super().__init__(
            f"{len(self.failures)} attempt(s) failed; last: "
            f"{type(last).__name__}: {last}"
        )


def run_with_recovery(
    make_attempt: Callable[[int], object],
    policy: RestartPolicy = RestartPolicy(),
    logger=None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Run ``make_attempt(attempt_index)`` under the restart policy.

    Returns whatever the first successful attempt returns. A non-retryable
    exception propagates immediately; retryable failures restart (with
    exponential backoff) until the budget is spent, then raise
    :class:`RestartsExhausted` chained to the last error.
    """
    failures: list[AttemptFailure] = []
    delay = policy.backoff_seconds
    for attempt in range(policy.max_restarts + 1):
        t0 = time.monotonic()
        try:
            return make_attempt(attempt)
        except BaseException as e:  # noqa: BLE001 - classified below
            took = time.monotonic() - t0
            if not policy.is_retryable(e):
                raise
            failures.append(
                AttemptFailure(attempt, type(e).__name__, str(e), took)
            )
            if logger is not None:
                logger.warning(
                    "attempt %d failed after %.1fs (%s: %s); %s",
                    attempt, took, type(e).__name__, e,
                    "restarting" if attempt < policy.max_restarts
                    else "budget exhausted",
                )
            if attempt >= policy.max_restarts:
                raise RestartsExhausted(failures, e) from e
            if delay > 0:
                sleep(delay)
            delay *= policy.backoff_multiplier
    raise AssertionError("unreachable")


# ---------------------------------------------------------------------------
# Multi-host failure detection


@dataclasses.dataclass
class PeerReport:
    """Result of a peer-liveness check."""

    alive: list[int]
    dead: list[int]          # stale heartbeat
    missing: list[int]       # never wrote one

    @property
    def healthy(self) -> bool:
        return not self.dead and not self.missing


class Heartbeat:
    """Per-process liveness beacon over a shared filesystem.

    Each process periodically rewrites ``<dir>/host-<process_id>.hb`` with a
    JSON payload (pid, wall time, beat count). Writes are atomic
    (tmp + ``os.replace``) so a reader never sees a torn file. Staleness is
    judged by the file's mtime on the shared filesystem — the same clock for
    all readers, so hosts need not have synchronized clocks.
    """

    def __init__(
        self,
        directory: str,
        process_id: Optional[int] = None,
        interval_seconds: float = 10.0,
    ):
        if process_id is None:
            import jax

            process_id = jax.process_index()
        self.directory = directory
        self.process_id = int(process_id)
        self.interval_seconds = interval_seconds
        self._stop = None
        self._thread = None
        self._beats = 0
        os.makedirs(directory, exist_ok=True)

    def _path(self, pid: int) -> str:
        return os.path.join(self.directory, f"host-{pid}.hb")

    def beat_once(self) -> None:
        self._beats += 1
        payload = {
            "process_id": self.process_id,
            "pid": os.getpid(),
            "time": time.time(),
            "beats": self._beats,
        }
        tmp = self._path(self.process_id) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(self.process_id))

    def start(self) -> "Heartbeat":
        import threading

        if self._thread is not None:
            return self
        self.beat_once()
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.interval_seconds):
                try:
                    self.beat_once()
                except OSError:
                    pass  # shared fs hiccup; next beat retries

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def check_peers(
        self,
        expected: Sequence[int],
        max_age_seconds: Optional[float] = None,
    ) -> PeerReport:
        """Classify each expected process id by heartbeat freshness.

        ``max_age_seconds`` defaults to 3x the beat interval (one missed
        beat is a scheduling blip; three is a dead or wedged host).

        Staleness is judged against OUR OWN heartbeat file's mtime, not the
        local clock: both timestamps then come from the same clock (the
        shared filesystem server's), so host-vs-fileserver skew cannot
        misclassify healthy peers. Falls back to local time if we have not
        beaten yet.
        """
        if max_age_seconds is None:
            max_age_seconds = 3.0 * self.interval_seconds
        try:
            now = os.path.getmtime(self._path(self.process_id))
        except OSError:
            now = time.time()
        alive, dead, missing = [], [], []
        for pid in expected:
            try:
                age = now - os.path.getmtime(self._path(pid))
            except OSError:
                missing.append(pid)
                continue
            (alive if age <= max_age_seconds else dead).append(pid)
        return PeerReport(alive=alive, dead=dead, missing=missing)
