"""Failure detection and elastic restart supervision.

Parity: the reference inherits ALL of its failure handling from the Spark
runtime (SURVEY.md §5.3): task retry, stage re-execution from RDD lineage,
speculative execution, executor-loss recompute. JAX has none of that — a lost
chip, a preempted host, or a failed collective kills the training process.
The rebuild's recovery model is checkpoint-restart (``checkpoint.py``
provides bit-identical resume) plus this module, which supplies the two
missing Spark-runtime equivalents:

* :func:`run_with_recovery` — the "task retry" analog. Runs a training
  attempt, classifies failures as retryable (device/runtime/IO errors,
  preemptions) or fatal (config bugs: ``ValueError``/``TypeError``, and
  user aborts), and restarts up to a budget with exponential backoff. Each
  attempt re-enters the driver pipeline, where ``--checkpoint-dir`` resume
  fast-forwards past completed coordinate steps — so unlike Spark's lineage
  recompute, no finished work is redone.

  Scope note (honest limits): in-process retry covers transient failures
  that leave the runtime usable — input IO errors, preemption signals
  delivered as exceptions, coordinator hiccups. A hard device loss can
  poison the XLA client for the whole process; for that case the driver
  exits nonzero after the restart budget and the outer scheduler's process
  restart (k8s/systemd restartPolicy) is the recovery path — the same
  division of labor as Spark (task retry in-process, executor relaunch by
  YARN). Both paths land in the same checkpoint resume.

* :class:`Heartbeat` — the "executor loss detection" analog for multi-host
  runs. Every process writes a heartbeat file into a shared directory (the
  checkpoint filesystem); :meth:`Heartbeat.check_peers` reports processes
  whose beat has gone stale. XLA collectives have no internal peer-failure
  timeout (Spark's netty RPC and NCCL both do), so without detection a
  surviving host blocks forever inside a psum whose peer died. The training
  driver checks peers between restart attempts and fails fast with the dead
  host list instead of hanging.

* :class:`PeerWatchdog` — LIVE detection during the solve. The
  between-attempts check above cannot fire while the main thread is wedged
  inside a collective; the watchdog monitors heartbeats from a daemon
  thread and hard-exits the process (``WATCHDOG_EXIT_CODE``) when peers go
  stale, so the outer scheduler's restart + checkpoint resume takes over.
"""
from __future__ import annotations

import dataclasses
import json
import os
import random
import time
from typing import Callable, Iterator, Optional, Sequence

from photon_tpu.faults import fault_point

__all__ = [
    "RestartPolicy",
    "RestartBudget",
    "AttemptFailure",
    "RestartsExhausted",
    "run_with_recovery",
    "RecoveryJournal",
    "RunSupervisor",
    "Heartbeat",
    "PeerReport",
    "PeerWatchdog",
    "WATCHDOG_EXIT_CODE",
    "MapCountWatchdog",
    "clear_executable_caches",
    "install_map_count_gauge",
]


# --------------------------------------------------- executable-cache bound
#
# jax's per-process executable caches hold mmap'd JIT code pages that are
# never released in-process; a long-lived driver compiling many distinct
# shapes (λ-sweep × bucketed RE shapes × restarts, or the autopilot looping
# bench stages) creeps toward ``vm.max_map_count``, at which point LLVM's
# code-page mmap ENOMEMs and jaxlib SEGFAULTS instead of raising — the
# round-5 1-in-2 suite crash, which conftest.py bounds for pytest ONLY
# (VERDICT r5 weak #5). These are the production-process equivalents: a
# watchdog that warns while there is still headroom to act, and an explicit
# cache-clear for config/λ boundaries where no live computation references
# the old executables.


class MapCountWatchdog:
    """Warn when this process's memory-map count nears ``vm.max_map_count``.

    ``check()`` reads ``/proc/self/maps`` (cheap: one readlines pass) and
    logs a loud warning once the used fraction crosses ``warn_fraction``
    (default 0.5 — half the budget gone means the next few thousand
    compiles are a countdown to a segfault, not an exception). Re-warns at
    most every ``rewarn_seconds`` and only while above the threshold, so a
    heartbeat-driven caller can check every beat for free. On platforms
    without procfs, ``check()`` reports ``maps=-1`` and never warns.
    """

    #: Linux default when /proc/sys/vm/max_map_count is unreadable.
    DEFAULT_MAX_MAP_COUNT = 65530

    def __init__(self, warn_fraction: float = 0.5,
                 rewarn_seconds: float = 300.0):
        if not 0.0 < warn_fraction <= 1.0:
            raise ValueError(f"warn_fraction must be in (0, 1], got "
                             f"{warn_fraction}")
        self.warn_fraction = warn_fraction
        self.rewarn_seconds = rewarn_seconds
        self._last_warn = 0.0

    @staticmethod
    def map_count() -> int:
        """Live memory-map count of this process, or -1 without procfs."""
        try:
            with open("/proc/self/maps", "rb") as f:
                return sum(1 for _ in f)
        except OSError:
            return -1

    @staticmethod
    def map_limit() -> int:
        try:
            with open("/proc/sys/vm/max_map_count") as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return MapCountWatchdog.DEFAULT_MAX_MAP_COUNT

    def check(self) -> dict:
        """One watchdog pass: ``{maps, limit, fraction, warned}``."""
        import logging

        maps = self.map_count()
        limit = self.map_limit()
        frac = (maps / limit) if (maps >= 0 and limit > 0) else 0.0
        warned = False
        now = time.monotonic()
        if frac >= self.warn_fraction and (
            now - self._last_warn >= self.rewarn_seconds
        ):
            self._last_warn = now
            warned = True
            logging.getLogger("photon_tpu.supervisor").warning(
                "memory-map count %d is %.0f%% of vm.max_map_count=%d — "
                "compiled-executable mmap growth is heading for an "
                "un-catchable jaxlib segfault (ENOMEM in LLVM's code-page "
                "mmap). Clear caches at the next config/λ boundary "
                "(supervisor.clear_executable_caches) or raise the sysctl.",
                maps, 100.0 * frac, limit,
            )
        return {"maps": maps, "limit": limit, "fraction": round(frac, 4),
                "warned": warned}


def install_map_count_gauge() -> None:
    """Register ``process_memory_maps`` callback gauge (idempotent)."""
    from photon_tpu.obs.metrics import REGISTRY

    REGISTRY.gauge_fn(
        "process_memory_maps",
        lambda: float(max(MapCountWatchdog.map_count(), 0)),
        "Live /proc/self/maps count (vm.max_map_count budget for mmap'd "
        "JIT code pages; see supervisor.MapCountWatchdog)",
    )


def clear_executable_caches(reason: str = "") -> None:
    """Drop jax's compiled-executable caches (and the retrace sentinel's
    warm state, so the recompiles that follow are expected, not alarms).

    Call ONLY at config/λ boundaries — points where no live computation
    references the old executables and the next program is a different
    static configuration anyway, so the recompile was going to happen
    regardless and the mmap'd code pages of the previous config are pure
    map-count growth.
    """
    import logging

    import jax

    from photon_tpu.obs import retrace

    jax.clear_caches()
    retrace.clear_warm()
    logging.getLogger("photon_tpu.supervisor").info(
        "cleared jax executable caches%s (map count now %d)",
        f" ({reason})" if reason else "", MapCountWatchdog.map_count(),
    )


def _default_retryable() -> tuple:
    """Exception types that plausibly heal on a restart: runtime/IO errors
    (includes jaxlib's XlaRuntimeError, which subclasses RuntimeError)."""
    return (RuntimeError, OSError, ConnectionError)


# Config bugs and user aborts: retrying cannot help, fail immediately even
# though some (e.g. a ValueError raised through a RuntimeError subclass
# hierarchy) might otherwise match.
_FATAL = (ValueError, TypeError, AssertionError, KeyboardInterrupt)


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    """How many times to restart and how to pace the attempts.

    Pacing uses DECORRELATED JITTER by default (``jitter=True``): each delay
    is ``min(max_backoff, uniform(backoff, 3 * previous_delay))``. Without
    it, every process of a multi-host job fails at the same collective and
    restarts in lockstep — a thundering herd against the shared checkpoint
    filesystem on every attempt. Jitter spreads the herd while keeping each
    host's expected pace exponential. ``seed`` pins the stream for tests;
    None seeds from OS entropy so hosts genuinely decorrelate.
    ``jitter=False`` restores exact exponential pacing
    (``backoff * multiplier^n``, capped at ``max_backoff_seconds``).
    """

    max_restarts: int = 3
    backoff_seconds: float = 1.0
    backoff_multiplier: float = 2.0
    max_backoff_seconds: float = 60.0
    jitter: bool = True
    seed: Optional[int] = None
    retryable: tuple = dataclasses.field(default_factory=_default_retryable)

    def is_retryable(self, err: BaseException) -> bool:
        if isinstance(err, _FATAL):
            return False
        return isinstance(err, self.retryable)

    def delays(self) -> Iterator[float]:
        """The (possibly jittered) inter-attempt delay sequence."""
        rng = random.Random(self.seed)
        delay = self.backoff_seconds
        while True:
            if self.jitter:
                delay = min(
                    self.max_backoff_seconds,
                    rng.uniform(
                        self.backoff_seconds,
                        max(self.backoff_seconds, 3.0 * delay),
                    ),
                )
                yield delay
            else:
                yield min(self.max_backoff_seconds, delay)
                delay *= self.backoff_multiplier


class RestartBudget:
    """Counted restart allowance with :class:`RestartPolicy` pacing — the
    supervision contract exported as a primitive other subsystems can
    hold.

    The control plane's ``replication_tailer_dead`` rule journals a
    restart REQUEST per firing; this budget is what makes the requests
    "within its restart budget" (ISSUE/docs/control.md): at most
    ``policy.max_restarts`` grants, spaced no tighter than the policy's
    decorrelated-jitter delay sequence. ``allow()`` returns True and
    consumes a grant, or False (exhausted / still inside the pacing
    window) — callers journal the refusal, they don't block on it."""

    def __init__(self, policy: RestartPolicy,
                 clock: Optional[Callable[[], float]] = None):
        self.policy = policy
        self._clock = clock or time.monotonic
        self._delays = policy.delays()
        self.spent = 0
        self._not_before: Optional[float] = None

    @property
    def remaining(self) -> int:
        return max(0, self.policy.max_restarts - self.spent)

    def allow(self) -> bool:
        if self.spent >= self.policy.max_restarts:
            return False
        now = self._clock()
        if self._not_before is not None and now < self._not_before:
            return False
        self.spent += 1
        self._not_before = now + next(self._delays)
        return True

    def snapshot(self) -> dict:
        return {"spent": self.spent, "remaining": self.remaining,
                "max_restarts": self.policy.max_restarts}


@dataclasses.dataclass
class AttemptFailure:
    """One failed attempt, for the supervision log. ``cause`` is the
    classified backend cause (``runtime/backend_guard``) when the failure
    went through :class:`RunSupervisor`; None for the plain retry loop."""

    attempt: int
    error_type: str
    message: str
    seconds: float
    cause: Optional[str] = None


class RestartsExhausted(RuntimeError):
    """Raised when every attempt in the budget failed; carries the history
    (and, via :attr:`cause`, the last classified backend cause when the
    attempts ran under a :class:`RunSupervisor`)."""

    def __init__(self, failures: Sequence[AttemptFailure], last: BaseException):
        self.failures = list(failures)
        self.last = last
        super().__init__(
            f"{len(self.failures)} attempt(s) failed; last: "
            f"{type(last).__name__}: {last}"
        )

    @property
    def cause(self) -> Optional[str]:
        return self.failures[-1].cause if self.failures else None


def run_with_recovery(
    make_attempt: Callable[[int], object],
    policy: RestartPolicy = RestartPolicy(),
    logger=None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Run ``make_attempt(attempt_index)`` under the restart policy.

    Returns whatever the first successful attempt returns. A non-retryable
    exception propagates immediately; retryable failures restart (with
    exponential backoff) until the budget is spent, then raise
    :class:`RestartsExhausted` chained to the last error.
    """
    failures: list[AttemptFailure] = []
    delays = policy.delays()
    for attempt in range(policy.max_restarts + 1):
        t0 = time.monotonic()
        try:
            return make_attempt(attempt)
        except BaseException as e:  # noqa: BLE001 - classified below
            took = time.monotonic() - t0
            if not policy.is_retryable(e):
                raise
            failures.append(
                AttemptFailure(attempt, type(e).__name__, str(e), took)
            )
            if logger is not None:
                logger.warning(
                    "attempt %d failed after %.1fs (%s: %s); %s",
                    attempt, took, type(e).__name__, e,
                    "restarting" if attempt < policy.max_restarts
                    else "budget exhausted",
                )
            if attempt >= policy.max_restarts:
                raise RestartsExhausted(failures, e) from e
            # OOM is deterministic-unless-degraded: the same shapes re-OOM
            # no matter how long we wait, so neither sleep on it nor DRAW
            # from the decorrelated-jitter schedule (a drawn-but-unslept
            # delay would still inflate the next transient's backoff) —
            # the TPU_RECOVERY.jsonl pattern of repeated identical
            # failures (runtime/memory_guard).
            from photon_tpu.runtime.memory_guard import is_oom

            delay = 0.0 if is_oom(e) else next(delays)
            if delay > 0:
                sleep(delay)
    raise AssertionError("unreachable")


# ---------------------------------------------------------------- supervision
#
# RunSupervisor formalizes what the ad-hoc TPU recovery tooling grew by
# hand (TPU_RECOVERY.jsonl: per-attempt {attempt, seconds, ok, tail, time}
# rows appended by scripts/tpu_recovery_daemon.py): classified restarts
# from checkpoints, an append-only machine-readable journal under the
# write_metrics_jsonl atomic O_APPEND contract, restart counters, and
# recovery.* trace events — docs/robustness.md §"Recovery journal".


class RecoveryJournal:
    """Append-only JSONL record of supervision events.

    Each row: ``{"time": <ISO-8601 UTC>, "event": <name>, "pid": ...,
    **fields}``. Writes go through ``utils.write_metrics_jsonl`` — one
    unbuffered whole-line O_APPEND write per row — so a supervisor restart
    racing the dying attempt's final record interleaves whole lines, never
    torn ones, and readers can tail the journal live. Every row is also
    mirrored as a ``recovery.<event>`` trace instant so a chaos drill's
    journal and timeline tell one story."""

    def __init__(self, path: str):
        self.path = path

    def record(self, event: str, _mirror: bool = True, **fields) -> None:
        """Append one row; ``_mirror=False`` skips the trace instant for
        events whose canonical instant is emitted elsewhere (e.g.
        ``backend_failover``, where ``backend_guard.record_failover`` owns
        the timeline event — one failover must be ONE event)."""
        from photon_tpu.obs import instant
        from photon_tpu.utils import write_metrics_jsonl

        row = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            # Sub-second wall stamp: the fleet journal merger
            # (obs/fleet.merge_journals) interleaves rows from concurrent
            # processes/attempts causally — the ISO second alone cannot
            # order a restart racing its predecessor's final record.
            "t": round(time.time(), 6),
            "event": event,
            "pid": os.getpid(),
            **fields,
        }
        try:
            write_metrics_jsonl(self.path, [row])
        except OSError:
            pass  # the journal is evidence, never a new failure mode
        if _mirror:
            instant(f"recovery.{event}", cat="recovery", **fields)


class RunSupervisor:
    """Checkpoint-resume restart supervision with classified causes.

    Wraps a training attempt factory exactly like :func:`run_with_recovery`
    (same :class:`RestartPolicy` decorrelated-jitter backoff, same
    retryable/fatal split, same ``--checkpoint-dir`` fast-forward contract)
    and adds the observability the ad-hoc recovery log proved necessary:

    * every failure is classified (``runtime/backend_guard``:
      init_unavailable / compile_error / device_lost / oom; plus
      ``preemption``/``io`` from the exception type) and counted in
      ``run_restarts_total{cause=...}``;
    * every attempt start/failure/success/exhaustion lands in the
      :class:`RecoveryJournal` and as a ``recovery.*`` trace instant;
    * under ``failover_policy="failover"`` a classified backend-level
      failure re-probes the backend between attempts and re-enters on CPU
      when the accelerator stays dead (the swap stamped via
      ``backend_guard.guard_snapshot`` — bench provenance and the PR 6
      gate then refuse accelerator comparisons), instead of burning every
      attempt on the same wedged grant.
    """

    def __init__(
        self,
        policy: RestartPolicy = RestartPolicy(),
        journal: Optional[object] = None,
        logger=None,
        failover_policy: str = "strict",
        sleep: Callable[[float], None] = time.sleep,
        compile_store: object = "auto",
    ):
        if isinstance(journal, str):
            journal = RecoveryJournal(journal)
        self.policy = policy
        self.journal = journal
        self.logger = logger
        self.failover_policy = failover_policy
        self.sleep = sleep
        # AOT compile-artifact store (runtime/compile_store.py): "auto"
        # resolves the process's active store at restart time; None
        # disables the between-attempt pre-warm; an explicit CompileStore
        # pins one (tests, bench drills).
        self.compile_store = compile_store

    def _store(self):
        if self.compile_store == "auto":
            from photon_tpu.runtime import compile_store as cs

            return cs.active()
        return self.compile_store

    @staticmethod
    def classify(err: BaseException) -> str:
        """Cause label for the restart counter/journal: the backend
        classification when it matches, else the exception family."""
        from photon_tpu.faults import PreemptionError
        from photon_tpu.runtime.backend_guard import (
            CAUSE_UNKNOWN,
            classify_backend_error,
        )

        if isinstance(err, PreemptionError):
            return "preemption"
        cause = classify_backend_error(err)
        if cause != CAUSE_UNKNOWN:
            return cause
        if isinstance(err, (OSError, ConnectionError)):
            return "io"
        return CAUSE_UNKNOWN

    def _journal(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.record(event, **fields)
        else:
            from photon_tpu.obs import instant

            instant(f"recovery.{event}", cat="recovery", **fields)

    def _maybe_failover(self, cause: str) -> None:
        """Between attempts, under the failover policy only: a backend-
        level failure re-probes in a subprocess (fresh deadline) and pins
        CPU when the accelerator is still dead. No live device arrays
        exist between attempts — each attempt rebuilds from checkpoint —
        so the full client re-init is safe HERE and only here."""
        if self.failover_policy != "failover":
            return
        from photon_tpu.runtime import backend_guard as bg

        if cause not in (bg.CAUSE_INIT_UNAVAILABLE, bg.CAUSE_DEVICE_LOST,
                         bg.CAUSE_COMPILE_ERROR):
            return
        probe = bg.probe_backend()
        if probe.ok:
            return
        # record_failover owns the canonical recovery.backend_failover
        # trace instant; the journal row is written un-mirrored so one
        # failover is ONE timeline event.
        if self.journal is not None:
            self.journal.record("backend_failover", _mirror=False,
                                to="cpu", cause=probe.cause,
                                reason=probe.reason)
        bg.record_failover(probe, logger=self.logger)
        try:
            from jax.extend.backend import clear_backends

            clear_backends()
        except Exception:  # noqa: BLE001 - version-dependent API
            pass

    def run(self, make_attempt: Callable[[int], object]):
        """Run ``make_attempt(attempt_index)`` under the policy; returns
        the first successful attempt's result. Non-retryable errors
        propagate immediately (journaled as ``fatal``); an exhausted
        budget raises :class:`RestartsExhausted` whose ``cause`` is the
        last classified failure.

        OOM policy (docs/robustness.md §"Memory pressure"): restarts
        cannot fix resource exhaustion, so an ``oom``-classified failure
        never burns the normal budget/backoff schedule — it is restarted
        AT MOST ONCE, immediately (no backoff sleep), pre-degraded
        (``memory_guard.pre_degrade_for_restart`` shrinks the sweep-cache
        budget and caps the RE chunk ladder, journaled as the plan the
        next attempt runs under); a second OOM escalates as a classified
        ``RestartsExhausted(cause="oom")``."""
        from photon_tpu.runtime import memory_guard as mg_mod

        # Register the journal for the attempt's lifetime so in-run OOM
        # downshifts land as journal rows next to the restart story —
        # restoring whatever was registered before, so a journal-less
        # supervisor can never detach an outer supervisor's journal.
        if self.journal is None:
            return self._run(make_attempt)
        prev_journal = mg_mod.set_journal(self.journal)
        try:
            return self._run(make_attempt)
        finally:
            mg_mod.set_journal(prev_journal)

    def _run(self, make_attempt: Callable[[int], object]):
        from photon_tpu.obs.metrics import REGISTRY

        restarts = REGISTRY.counter(
            "run_restarts_total",
            "training restarts/recoveries by classified cause "
            "(docs/robustness.md §recovery journal)",
        )
        from photon_tpu.runtime import compile_store as cs_mod

        failures: list[AttemptFailure] = []
        delays = self.policy.delays()
        attempt = 0
        oom_restarts = 0
        other_restarts = 0
        while True:
            t0 = time.monotonic()
            self._journal("attempt_start", attempt=attempt)
            # restart→first-step clock (docs/robustness.md §recovery time):
            # the attempt's first committed training step closes it
            # (descent stamps it), journaling restart_to_first_step_seconds
            # and setting the gauge /healthz and bench read.
            cs_mod.arm_first_step_clock(attempt=attempt, journal=self.journal)
            try:
                result = make_attempt(attempt)
            except BaseException as e:  # noqa: BLE001 - classified below
                took = round(time.monotonic() - t0, 3)
                cause = self.classify(e)
                retryable = self.policy.is_retryable(e)
                from photon_tpu.runtime.backend_guard import CAUSE_OOM

                is_oom_failure = cause == CAUSE_OOM
                if is_oom_failure:
                    # The one pre-degraded OOM restart rides OUTSIDE the
                    # transient budget (a capacity wall and a flaky device
                    # are different failure classes, and charging the OOM
                    # retry against max_restarts would shortchange later
                    # genuine transients). A PRE-DEGRADED attempt that
                    # still OOMs is a doomed loop, not recovery; a zero
                    # budget still means "never restart anything".
                    will_restart = (retryable and oom_restarts < 1
                                    and self.policy.max_restarts > 0)
                else:
                    will_restart = (retryable and other_restarts
                                    < self.policy.max_restarts)
                failures.append(AttemptFailure(
                    attempt, type(e).__name__, str(e), took, cause=cause))
                self._journal(
                    "attempt_failed", attempt=attempt, cause=cause,
                    error=f"{type(e).__name__}: {str(e)[:300]}",
                    seconds=took, ok=False, will_restart=will_restart)
                if self.logger is not None:
                    self.logger.warning(
                        "attempt %d failed after %.1fs [%s] (%s: %s); %s",
                        attempt, took, cause, type(e).__name__, e,
                        "restarting" if will_restart
                        else "fatal" if not retryable else "budget exhausted")
                if not retryable:
                    cs_mod.disarm_first_step_clock()
                    self._journal("fatal", attempt=attempt, cause=cause)
                    raise
                if not will_restart:
                    cs_mod.disarm_first_step_clock()
                    self._journal("exhausted", attempts=len(failures),
                                  cause=cause)
                    raise RestartsExhausted(failures, e) from e
                restarts.inc(cause=cause)
                self._maybe_failover(cause)
                if is_oom_failure:
                    # The one OOM restart goes out PRE-DEGRADED: same
                    # shapes would deterministically re-OOM, so the next
                    # attempt gets a shrunken sweep-cache budget and a
                    # capped RE chunk ladder (journaled plan).
                    from photon_tpu.runtime import memory_guard as mg_mod

                    oom_restarts += 1
                    mg_mod.pre_degrade_for_restart(
                        f"attempt {attempt} oom: {str(e)[:120]}")
                else:
                    other_restarts += 1
                # Pre-warm the NEXT attempt from the compile store's
                # manifest: every executable the failed attempt compiled
                # loads from the persistent cache before the restart goes
                # live, so the retry's restart-to-first-step is I/O-bound,
                # not XLA-bound. prewarm() emits the recovery.prewarm trace
                # instant itself; the journal row is written un-mirrored so
                # one pre-warm is ONE timeline event.
                store = self._store()
                if store is not None:
                    try:
                        summary = store.prewarm(
                            logger_=self.logger,
                            reason=f"restart attempt {attempt + 1}")
                    except Exception as pe:  # noqa: BLE001 - never re-fail
                        summary = None
                        if self.logger is not None:
                            self.logger.warning(
                                "compile-store prewarm failed (%s: %s); "
                                "restarting cold", type(pe).__name__, pe)
                    if summary is not None and self.journal is not None:
                        self.journal.record(
                            "prewarm", _mirror=False,
                            attempt=attempt + 1, **summary)
                # OOM skips the backoff sleep entirely (deterministic-
                # unless-degraded — waiting cannot free device memory the
                # plan shrink didn't; the jitter schedule is preserved for
                # genuinely transient causes).
                delay = 0.0 if is_oom_failure else next(delays)
                self._journal("restart", attempt=attempt + 1, cause=cause,
                              backoff_s=round(delay, 3))
                if delay > 0:
                    self.sleep(delay)
                attempt += 1
                continue
            took = round(time.monotonic() - t0, 3)
            cs_mod.disarm_first_step_clock()  # a stepless success (full
            # checkpoint fast-forward) must not leave a stale armed clock
            self._journal("run_ok", attempt=attempt, seconds=took, ok=True,
                          prior_failures=len(failures))
            return result


# ---------------------------------------------------------------------------
# Multi-host failure detection


@dataclasses.dataclass
class PeerReport:
    """Result of a peer-liveness check."""

    alive: list[int]
    dead: list[int]          # stale heartbeat
    missing: list[int]       # never wrote one

    @property
    def healthy(self) -> bool:
        return not self.dead and not self.missing


class Heartbeat:
    """Per-process liveness beacon over a shared filesystem.

    Each process periodically rewrites ``<dir>/host-<process_id>.hb`` with a
    JSON payload (pid, wall time, beat count). Writes are atomic
    (tmp + ``os.replace``) so a reader never sees a torn file. Staleness is
    judged by the file's mtime on the shared filesystem — the same clock for
    all readers, so hosts need not have synchronized clocks.
    """

    def __init__(
        self,
        directory: str,
        process_id: Optional[int] = None,
        interval_seconds: float = 10.0,
        slo_watchdog=None,
        memory_guard="auto",
        peer_gauges: Optional[Sequence[int]] = None,
    ):
        if process_id is None:
            import jax

            process_id = jax.process_index()
        self.directory = directory
        self.process_id = int(process_id)
        self.interval_seconds = interval_seconds
        # Optional obs.analysis.slo.SloWatchdog: SLO rules judged on the
        # beat cadence (rate-limited by the watchdog's own min_interval_s)
        # from the same surviving daemon thread as the map-count check, so
        # a wedged main thread still reports SLO state.
        self.slo_watchdog = slo_watchdog
        # Device-memory watchdog (runtime/memory_guard): every long-lived
        # training process already heartbeats, so the memory sample +
        # high-water sweep-cache spill ride the same loop for free.
        # "auto" resolves the process guard at start(); None disables.
        self.memory_guard = memory_guard
        # Expected peer ids whose beacon ages this process exports as
        # ``host_beacon_age_seconds{host=...}`` gauges on every beat — the
        # fleet report and live /fleet then show a dead host (age frozen
        # and climbing, or -1 for never-seen) without reading journals.
        self.peer_gauges = (None if peer_gauges is None
                            else [int(p) for p in peer_gauges])
        self.epoch = 0
        self._stop = None
        self._thread = None
        self._beats = 0
        os.makedirs(directory, exist_ok=True)

    def _path(self, pid: int) -> str:
        return os.path.join(self.directory, f"host-{pid}.hb")

    def beat_once(self) -> None:
        import threading

        # Chaos hook: an injected OSError here makes THIS process's beat go
        # stale while it keeps running — the failure mode peers must detect.
        fault_point("heartbeat.beat", process_id=self.process_id)
        self._beats += 1
        payload = {
            "process_id": self.process_id,
            "pid": os.getpid(),
            "time": time.time(),
            "beats": self._beats,
            "epoch": self.epoch,
        }
        # Thread-unique tmp name: set_epoch beats from the caller's thread
        # while the background loop beats on its own schedule; a shared tmp
        # path would let one writer os.replace the other's file away mid-
        # rename (FileNotFoundError out of a harmless race).
        tmp = (
            f"{self._path(self.process_id)}.tmp{os.getpid()}"
            f".{threading.get_ident()}"
        )
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(self.process_id))

    def set_epoch(self, epoch: int) -> None:
        """Advertise this process's attempt epoch (and beat immediately).

        Multi-host in-process retry is only safe when EVERY host re-enters
        the attempt together — a host retrying alone issues collectives that
        mismatch a peer still blocked in the previous attempt's psum, and
        both then hang with perfectly fresh heartbeats. The epoch in the
        beat payload is what :meth:`wait_for_epoch` synchronizes on.
        """
        self.epoch = int(epoch)
        self.beat_once()

    def peer_epochs(self, expected: Sequence[int]) -> dict:
        """Last advertised attempt epoch per peer (-1: no/unreadable beat)."""
        out = {}
        for pid in expected:
            try:
                with open(self._path(pid)) as f:
                    out[pid] = int(json.load(f).get("epoch", -1))
            except (OSError, ValueError):
                out[pid] = -1
        return out

    def wait_for_epoch(
        self,
        expected: Sequence[int],
        epoch: int,
        timeout_seconds: float = 30.0,
        poll_seconds: Optional[float] = None,
    ) -> list:
        """Block until every expected peer advertises ``epoch`` or newer;
        returns the laggards (empty = barrier passed). A peer wedged inside
        the previous attempt's collective never advances its epoch, so the
        caller can fail fast instead of desynchronizing the retry."""
        poll = self.interval_seconds if poll_seconds is None else poll_seconds
        deadline = time.monotonic() + timeout_seconds
        while True:
            epochs = self.peer_epochs(expected)
            laggards = [p for p, e in epochs.items() if e < epoch]
            if not laggards or time.monotonic() >= deadline:
                return laggards
            time.sleep(poll)

    def start(self) -> "Heartbeat":
        import threading

        if self._thread is not None:
            return self
        self.beat_once()
        self._stop = threading.Event()
        # Executable-cache growth watch rides the liveness loop: every
        # long-lived training process already heartbeats, so the map-count
        # check (one /proc read) costs nothing extra and warns from the
        # same thread that survives a wedged main thread. The gauge makes
        # the same number scrapeable wherever /metrics is served.
        map_watch = MapCountWatchdog()
        install_map_count_gauge()
        mem_guard = self.memory_guard
        if mem_guard == "auto":
            from photon_tpu.runtime.memory_guard import guard

            mem_guard = guard()

        def loop():
            while not self._stop.wait(self.interval_seconds):
                try:
                    self.beat_once()
                except OSError:
                    pass  # shared fs hiccup; next beat retries
                try:
                    self.export_peer_gauges()
                except Exception:  # noqa: BLE001 - gauge export must
                    pass  # never take the liveness beacon down with it
                map_watch.check()
                if mem_guard is not None:
                    try:
                        mem_guard.check()
                    except Exception:  # noqa: BLE001 - the watchdog must
                        pass  # never take the liveness beacon down with it
                if self.slo_watchdog is not None:
                    try:
                        self.slo_watchdog.check()
                    except Exception:  # noqa: BLE001 - SLO judgment must
                        pass  # never take the liveness beacon down with it

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def export_peer_gauges(
        self, expected: Optional[Sequence[int]] = None
    ) -> None:
        """Export ``host_beacon_age_seconds{host=...}`` for each expected
        peer (default: the ``peer_gauges`` set; no-op when unset). Age is
        judged like :meth:`check_peers` — against our own beacon's mtime,
        the shared filesystem's clock — and a host with no beacon file
        exports -1 (never seen / file vanished)."""
        expected = self.peer_gauges if expected is None else expected
        if not expected:
            return
        from photon_tpu.obs.metrics import REGISTRY

        gauge = REGISTRY.gauge(
            "host_beacon_age_seconds",
            "Seconds since each expected host's last liveness beacon "
            "(-1: no beacon file); a frozen, climbing age is a dead host",
        )
        try:
            now = os.path.getmtime(self._path(self.process_id))
        except OSError:
            now = time.time()
        for pid in expected:
            try:
                age = max(0.0, now - os.path.getmtime(self._path(pid)))
            except OSError:
                age = -1.0
            gauge.set(age, host=str(pid))

    def watchdog(
        self,
        expected: Sequence[int],
        **kwargs,
    ) -> "PeerWatchdog":
        """A :class:`PeerWatchdog` over this beacon (sugar for the driver)."""
        return PeerWatchdog(self, expected, **kwargs)

    def check_peers(
        self,
        expected: Sequence[int],
        max_age_seconds: Optional[float] = None,
    ) -> PeerReport:
        """Classify each expected process id by heartbeat freshness.

        ``max_age_seconds`` defaults to 3x the beat interval (one missed
        beat is a scheduling blip; three is a dead or wedged host).

        Staleness is judged against OUR OWN heartbeat file's mtime, not the
        local clock: both timestamps then come from the same clock (the
        shared filesystem server's), so host-vs-fileserver skew cannot
        misclassify healthy peers. Falls back to local time if we have not
        beaten yet.
        """
        if max_age_seconds is None:
            max_age_seconds = 3.0 * self.interval_seconds
        try:
            now = os.path.getmtime(self._path(self.process_id))
        except OSError:
            now = time.time()
        alive, dead, missing = [], [], []
        for pid in expected:
            try:
                age = now - os.path.getmtime(self._path(pid))
            except OSError:
                missing.append(pid)
                continue
            (alive if age <= max_age_seconds else dead).append(pid)
        return PeerReport(alive=alive, dead=dead, missing=missing)


WATCHDOG_EXIT_CODE = 43  # distinct from restart-budget exits; scheduler-visible


class PeerWatchdog:
    """Live peer monitor that aborts a hung process DURING the solve.

    A collective whose peer died blocks forever inside the XLA runtime — no
    Python exception can interrupt it, so the between-attempts
    ``check_peers`` in the retry loop never runs (round-3 scope note). This
    daemon thread checks peer heartbeats every ``check_interval_seconds``
    while the solve is in flight; after ``grace_checks`` CONSECUTIVE
    unhealthy reports it invokes ``on_dead(report)`` — by default: write
    ``<dir>/watchdog-abort.json`` for the postmortem, log, and
    ``os._exit(WATCHDOG_EXIT_CODE)``. A nonzero exit hands recovery to the
    outer scheduler (k8s/systemd restartPolicy), whose process restart lands
    in checkpoint resume — the same division of labor as Spark's executor
    relaunch under YARN.

    ``os._exit``, not ``sys.exit``: the main thread is wedged in C++ and will
    never unwind; only a hard process exit releases it.
    """

    def __init__(
        self,
        heartbeat: Heartbeat,
        expected: Sequence[int],
        check_interval_seconds: Optional[float] = None,
        max_age_seconds: Optional[float] = None,
        grace_checks: int = 2,
        startup_grace_seconds: float = 120.0,
        on_dead: Optional[Callable[[PeerReport], None]] = None,
        logger=None,
    ):
        self.heartbeat = heartbeat
        self.expected = [int(p) for p in expected]
        self.check_interval_seconds = (
            heartbeat.interval_seconds
            if check_interval_seconds is None
            else check_interval_seconds
        )
        self.max_age_seconds = max_age_seconds
        self.grace_checks = max(1, int(grace_checks))
        # A peer that has NEVER been seen is distinct from one that stopped:
        # startup skew or shared-fs attribute caching (NFS acdirmin) can hide
        # a healthy peer's fresh file for many seconds. Never-seen peers only
        # count as unhealthy after this grace; once seen, vanishing or going
        # stale counts immediately.
        self.startup_grace_seconds = startup_grace_seconds
        self.on_dead = on_dead if on_dead is not None else self._abort
        self.logger = logger
        self.fired: Optional[PeerReport] = None
        self._seen: set = set()
        self._stop = None
        self._thread = None

    def _abort(self, report: PeerReport) -> None:
        try:
            payload = {
                "process_id": self.heartbeat.process_id,
                "time": time.time(),
                "dead": report.dead,
                "missing": report.missing,
                "alive": report.alive,
            }
            path = os.path.join(
                self.heartbeat.directory,
                f"watchdog-abort.host-{self.heartbeat.process_id}.json",
            )
            with open(path + ".tmp", "w") as f:
                json.dump(payload, f)
            os.replace(path + ".tmp", path)
        except OSError:
            pass  # the exit below is the point; the breadcrumb is best-effort
        if self.logger is not None:
            self.logger.error(
                "peer watchdog: dead=%s missing=%s — aborting for scheduler "
                "restart (exit %d; checkpoint resume fast-forwards)",
                report.dead, report.missing, WATCHDOG_EXIT_CODE,
            )
        os._exit(WATCHDOG_EXIT_CODE)

    def start(self) -> "PeerWatchdog":
        import threading

        if self._thread is not None:
            return self
        self._stop = threading.Event()

        started = time.monotonic()

        def loop():
            strikes = 0
            while not self._stop.wait(self.check_interval_seconds):
                try:
                    report = self.heartbeat.check_peers(
                        self.expected, self.max_age_seconds
                    )
                except OSError:
                    continue  # shared fs hiccup; next check retries
                self._seen.update(report.alive)
                self._seen.update(report.dead)  # a stale file was still seen
                in_grace = (
                    time.monotonic() - started < self.startup_grace_seconds
                )
                unhealthy = bool(report.dead) or any(
                    # missing-after-seen = vanished peer; missing-never-seen
                    # only counts once the startup grace has elapsed
                    (p in self._seen) or not in_grace
                    for p in report.missing
                )
                strikes = strikes + 1 if unhealthy else 0
                if strikes >= self.grace_checks:
                    self.fired = report
                    self.on_dead(report)
                    return

        self._thread = threading.Thread(
            target=loop, daemon=True, name="photon-peer-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "PeerWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
