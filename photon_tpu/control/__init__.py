"""Closed-loop control plane (docs/control.md).

Observation (PR 15's telemetry + the replicas' live HTTP surfaces) feeds a
declarative policy engine whose decisions actuate existing levers — PR 12's
standby+swap, PR 13's memory shed, PR 16's tailer restart, the batcher's
reconfigure — with hysteresis, per-lever cooldowns, and budgets so the loop
provably damps. Every decision is journaled to ``control-ledger.jsonl``
under the PR 15 journal contract. Importable without jax: the control
driver runs on boxes that never load an accelerator runtime.
"""
from photon_tpu.control.actions import LeverError, Levers, promote_wave
from photon_tpu.control.controller import Controller, ReplicaTarget
from photon_tpu.control.ledger import (
    LEDGER_FILENAME,
    ControlLedger,
    read_ledger,
)
from photon_tpu.control.policy import (
    AutoscalePolicy,
    CanaryPolicy,
    ControlPolicy,
    Decision,
    PolicyEngine,
    Rule,
)

__all__ = [
    "AutoscalePolicy",
    "CanaryPolicy",
    "Controller",
    "ControlLedger",
    "ControlPolicy",
    "Decision",
    "LEDGER_FILENAME",
    "LeverError",
    "Levers",
    "PolicyEngine",
    "ReplicaTarget",
    "Rule",
    "promote_wave",
    "read_ledger",
]
