"""Lever bindings: the control plane's hands (docs/control.md §levers).

Every actuator the policy engine can fire is a small, synchronous HTTP
call against machinery that already exists — the control plane adds NO new
failure-handling mechanism, it only pulls levers other PRs built and
hardened:

==================  ====================================================
lever               binding
==================  ====================================================
``standby_swap``    ``POST /admin/standby`` then ``POST /admin/swap``
                    (PR 12: warm off the hot path, then a pointer move)
``shed_cache``      ``POST /admin/memory/shed`` (PR 13 memory guard
                    sweep, invoked proactively on a watermark ramp)
``restart_tailer``  ``POST /admin/replication/restart`` (PR 16 tailer's
                    ``start()`` restart contract, within budget)
``scale_batcher``   ``POST /admin/tune`` (micro-batcher reconfigure)
``promote_wave``    append canary-log deltas to the MAIN delta log
                    (``replication/log.DeltaLogWriter`` — non-canary
                    replicas only ever see promoted waves)
``rollback``        ``standby``+``swap`` back to the base model dir (the
                    versioned overlay makes this a pointer move), then
                    resync: re-feed the promoted mainline deltas
==================  ====================================================

All calls raise :class:`LeverError` on transport/HTTP failure; the
controller journals the outcome either way — an actuation that failed is
MORE important evidence than one that worked.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from photon_tpu.replication.log import DeltaLogRecord, DeltaLogWriter

__all__ = ["LeverError", "Levers", "promote_wave"]


class LeverError(RuntimeError):
    """An actuation failed (transport error or non-2xx reply)."""


def _request(url: str, payload: Optional[dict], timeout_s: float,
             headers: Optional[dict] = None) -> dict:
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, headers={
        **({"Content-Type": "application/json"} if data else {}),
        **(headers or {}),
    })
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        detail = e.read().decode("utf-8", "replace")[:200]
        raise LeverError(f"{url}: HTTP {e.code}: {detail}") from None
    except (urllib.error.URLError, TimeoutError, OSError) as e:
        raise LeverError(f"{url}: {type(e).__name__}: {e}") from None
    try:
        return json.loads(body) if body else {}
    except json.JSONDecodeError:
        raise LeverError(f"{url}: non-JSON reply: {body[:120]!r}") from None


class Levers:
    """HTTP actuators against one fleet. Stateless; per-call timeout."""

    def __init__(self, timeout_s: float = 10.0):
        self.timeout_s = timeout_s

    # -- observation calls (GET) ----------------------------------------
    def healthz(self, base_url: str) -> dict:
        return _request(base_url.rstrip("/") + "/healthz", None,
                        self.timeout_s)

    def metrics(self, base_url: str) -> dict:
        return _request(base_url.rstrip("/") + "/metrics", None,
                        self.timeout_s)

    def score(self, base_url: str, rows: Sequence[dict]) -> tuple[float, dict]:
        """POST each probe row to /score (the server scores one row per
        request); returns (mean per-row round-trip ms, {"scores": [...]}).
        The round-trip is the controller's per-tick latency sample —
        windowed by construction, unlike the server's lifetime histogram."""
        url = base_url.rstrip("/") + "/score"
        scores = []
        t0 = time.monotonic()
        for row in rows:
            out = _request(url, dict(row), self.timeout_s)
            scores.append(out.get("score"))
        elapsed_ms = (time.monotonic() - t0) * 1e3
        return elapsed_ms / max(1, len(scores)), {"scores": scores}

    # -- actuators (POST) ------------------------------------------------
    def prepare_standby(self, base_url: str, model_dir: str) -> dict:
        return _request(base_url.rstrip("/") + "/admin/standby",
                        {"model_dir": model_dir}, self.timeout_s)

    def swap(self, base_url: str, model_dir: str) -> dict:
        return _request(base_url.rstrip("/") + "/admin/swap",
                        {"model_dir": model_dir}, self.timeout_s)

    def standby_swap(self, base_url: str, model_dir: str) -> dict:
        """The PR 12 two-step: warm off the hot path, then pointer-move.
        A swap without the standby warm-up would trade a latency shift for
        a retrace stall — exactly the wrong remediation."""
        prepared = self.prepare_standby(base_url, model_dir)
        swapped = self.swap(base_url, model_dir)
        return {"prepared": prepared, "swapped": swapped}

    def shed_cache(self, base_url: str) -> dict:
        return _request(base_url.rstrip("/") + "/admin/memory/shed",
                        {}, self.timeout_s)

    def restart_tailer(self, base_url: str) -> dict:
        return _request(base_url.rstrip("/") + "/admin/replication/restart",
                        {}, self.timeout_s)

    def tune_batcher(self, base_url: str, max_batch: int,
                     max_queue: Optional[int] = None) -> dict:
        payload: dict = {"max_batch": int(max_batch)}
        if max_queue is not None:
            payload["max_queue"] = int(max_queue)
        return _request(base_url.rstrip("/") + "/admin/tune",
                        payload, self.timeout_s)

    def post_patch(self, base_url: str, wire_delta: dict,
                   idempotency_key: Optional[str] = None,
                   trace_id: Optional[str] = None) -> dict:
        headers = {}
        if idempotency_key:
            headers["X-Photon-Idempotency-Key"] = idempotency_key
        if trace_id:
            headers["X-Photon-Trace-Id"] = trace_id
        return _request(base_url.rstrip("/") + "/admin/patch",
                        wire_delta, self.timeout_s, headers=headers)


def promote_wave(writer: DeltaLogWriter,
                 records: Sequence[DeltaLogRecord]) -> list[int]:
    """Append a soaked canary wave's delta records to the main log.

    Each log is dense in its OWN seq space — the writer assigns fresh
    mainline seqs, so the canary side channel and the main log never need
    coordinated numbering (and a rolled-back wave simply never shows up
    here). Snapshot markers are not promoted: the main log carries its own
    base marker. Returns the assigned mainline seqs."""
    seqs: list[int] = []
    for rec in records:
        if rec.delta is None:
            continue
        seqs.append(writer.append(rec.delta, trace_id=rec.trace_id))
    return seqs
