"""The closed-loop controller: observe → decide → actuate → journal.

One tick (``ControlPolicy.tick_s``):

1. **Observe** every target replica — ``GET /healthz`` (degraded reasons,
   replication watermark), ``GET /metrics`` (batcher queue depth,
   memory watermark, error counters), and one probe ``POST /score`` whose
   round-trip is the tick's latency sample (the server histogram is
   lifetime-cumulative; the probe series is windowed by construction).
2. **Decide** via :class:`~photon_tpu.control.policy.PolicyEngine` — the
   hysteresis / cooldown / budget gates live there, so the controller
   never has to reason about restraint.
3. **Actuate** through :class:`~photon_tpu.control.actions.Levers` — every
   lever is pre-existing machinery (standby+swap, memory shed, tailer
   restart, batcher tune).
4. **Journal** everything to the :class:`ControlLedger` — observation,
   rule, action, outcome — so a chaos drill can prove, from the ledger
   alone, that the loop converged instead of oscillated.

The canary protocol (docs/control.md) runs alongside: the online trainer
publishes waves into a SIDE-CHANNEL delta log tailed only by the canary
replica; this controller owns the MAIN log's writer, so a wave reaches
non-canary replicas only by surviving its soak (probe drift vs a
reference replica + latency/error gates) and being promoted — and a
poisoned wave is rolled back by a pointer move to the base model dir plus
a mainline resync, never having touched the fleet.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from photon_tpu.control.actions import LeverError, Levers, promote_wave
from photon_tpu.control.ledger import ControlLedger
from photon_tpu.control.policy import ControlPolicy, Decision, PolicyEngine
from photon_tpu.obs.metrics import MetricsRegistry
from photon_tpu.replication.log import (
    DeltaLogWriter,
    iter_log,
    log_next_seq,
    pending_records,
)

__all__ = ["ReplicaTarget", "Controller"]


class ReplicaTarget:
    """One replica under control. ``url`` doubles as its ledger identity
    (the router names replicas the same way)."""

    def __init__(self, url: str, canary: bool = False):
        self.url = url.rstrip("/")
        self.canary = bool(canary)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ReplicaTarget({self.url!r}, canary={self.canary})"


class _CanaryState:
    __slots__ = ("phase", "wave_start", "wave_end", "settle_left",
                 "probes", "records")

    def __init__(self):
        self.phase = "idle"          # idle | settling | soaking
        self.wave_start = 0          # first canary-log seq of the wave
        self.wave_end = 0            # one past the last seq of the wave
        self.settle_left = 0
        self.probes: list[dict] = []
        self.records: list = []


class Controller:
    """Tick loop binding policy to levers for one replica fleet.

    ``probe_rows`` drive both the latency sample and the canary drift
    probe; without them the controller falls back to ``/healthz``
    round-trips for latency and promotes canary waves on health alone
    (journaled as ``drift: null`` so the weaker verdict is visible)."""

    def __init__(
        self,
        policy: ControlPolicy,
        replicas: Sequence[ReplicaTarget],
        ledger: ControlLedger,
        *,
        main_log_path: Optional[str] = None,
        canary_log_path: Optional[str] = None,
        base_model_dir: Optional[str] = None,
        probe_rows: Optional[Sequence[dict]] = None,
        router_url: Optional[str] = None,
        levers: Optional[Levers] = None,
        restart_policy=None,
        logger=None,
        clock=None,
    ):
        self.policy = policy
        self.replicas = list(replicas)
        self.ledger = ledger
        self.main_log_path = main_log_path
        self.canary_log_path = canary_log_path
        self.base_model_dir = base_model_dir
        self.probe_rows = list(probe_rows or ())
        self.router_url = router_url.rstrip("/") if router_url else None
        self.levers = levers or Levers()
        self.logger = logger
        self.engine = PolicyEngine(policy, clock=clock)
        # Restart requests ride the supervisor's own budget contract: at
        # most max_restarts grants per target, paced by the policy's
        # decorrelated-jitter delays (photon_tpu.supervisor.RestartBudget).
        self._restart_policy = restart_policy
        self._restart_budgets: dict = {}
        self.ticks = 0
        self.actions_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._canary = _CanaryState()
        self._main_writer: Optional[DeltaLogWriter] = None
        self._canary_next = 0  # first canary-log seq not yet adjudicated

        canaries = [r for r in self.replicas if r.canary]
        if len(canaries) > 1:
            raise ValueError("at most one canary replica")
        self.canary_replica = canaries[0] if canaries else None
        self.reference_replica = next(
            (r for r in self.replicas if not r.canary), None)

        self.metrics = MetricsRegistry()
        self._ticks_c = self.metrics.counter(
            "control_ticks_total", "controller loop iterations")
        self._actions_c = self.metrics.counter(
            "control_actions_total", "lever actuations by action")
        self._suppressed_c = self.metrics.counter(
            "control_suppressed_total",
            "rule firings vetoed by cooldown/budget")
        self._verdicts_c = self.metrics.counter(
            "control_canary_verdicts_total", "canary waves adjudicated")

        if self.canary_replica is not None:
            if not (main_log_path and canary_log_path):
                raise ValueError(
                    "canary control needs main_log_path and canary_log_path")
            if not base_model_dir:
                raise ValueError("canary rollback needs base_model_dir")
            self._main_writer = DeltaLogWriter(main_log_path)
            if self._main_writer.next_seq == 0:
                # The controller owns the main log: the base marker anchors
                # catch-up for replicas booting before any promotion.
                self._main_writer.append_snapshot(
                    base_model_dir, note="canary-control base")
            # Adjudicate only waves published AFTER the controller came up:
            # pre-existing canary-log records were either already promoted
            # by a prior controller incarnation or predate control entirely
            # — re-promoting them would duplicate mainline records.
            self._canary_next = log_next_seq(canary_log_path)

    # ------------------------------------------------------------------
    # observation
    # ------------------------------------------------------------------
    def _observe(self, target: ReplicaTarget) -> Optional[dict]:
        """One tick's signals for ``target``; None when unreachable."""
        signals: dict = {}
        try:
            if self.probe_rows:
                latency_ms, _ = self.levers.score(target.url, self.probe_rows)
            else:
                t0 = time.monotonic()
                self.levers.healthz(target.url)
                latency_ms = (time.monotonic() - t0) * 1e3
            signals["probe_latency_ms"] = latency_ms
            health = self.levers.healthz(target.url)
            metrics = self.levers.metrics(target.url)
        except LeverError as e:
            self.ledger.record(
                "observation", target=target.url, error=str(e)[:200])
            return None
        degraded = health.get("degraded") or []
        signals["tailer_dead"] = (
            1.0 if "replication_tailer_dead" in degraded else 0.0)
        mem = metrics.get("memory") or {}
        if mem.get("watermark") is not None:
            signals["memory_watermark"] = float(mem["watermark"])
        lat = metrics.get("latency") or {}
        if lat.get("p95_ms") is not None:
            signals["latency_p95_ms"] = float(lat["p95_ms"])
        batcher = metrics.get("batcher") or {}
        max_queue = batcher.get("max_queue") or 0
        if max_queue:
            signals["queue_frac"] = (
                float(batcher.get("queued") or 0) / float(max_queue))
        signals["errors"] = float(metrics.get("errors") or 0)
        # Tick-scoped context (not series): autoscaler sizing inputs and
        # the replication watermark for canary settle tracking.
        signals["_max_batch"] = batcher.get("max_batch")
        signals["_max_queue"] = max_queue
        rep = health.get("replication") or {}
        signals["_replication_watermark"] = rep.get("seq_watermark")
        signals["_model_version"] = health.get("model_version")
        signals["_degraded"] = degraded
        return signals

    # ------------------------------------------------------------------
    # actuation
    # ------------------------------------------------------------------
    def _dispatch(self, d: Decision) -> dict:
        if d.action == "standby_swap":
            if not self.base_model_dir:
                raise LeverError("standby_swap needs base_model_dir")
            return self.levers.standby_swap(d.target, self.base_model_dir)
        if d.action == "shed_cache":
            return self.levers.shed_cache(d.target)
        if d.action == "restart_tailer":
            if self._restart_policy is not None:
                from photon_tpu.supervisor import RestartBudget

                budget = self._restart_budgets.get(d.target)
                if budget is None:
                    budget = self._restart_budgets[d.target] = (
                        RestartBudget(self._restart_policy))
                if not budget.allow():
                    raise LeverError(
                        f"restart budget refused ({budget.snapshot()})")
            return self.levers.restart_tailer(d.target)
        if d.action == "scale_batcher":
            return self.levers.tune_batcher(
                d.target, d.params["max_batch"], d.params.get("max_queue"))
        raise LeverError(f"unknown action {d.action!r}")

    def _actuate(self, decisions: Sequence[Decision]) -> None:
        for d in decisions:
            self.ledger.record(
                "rule_fired", rule=d.rule, target=d.target, **d.evidence)
            self.ledger.record(
                "action", action=d.action, target=d.target,
                rule=d.rule, params=d.params)
            self._actions_c.inc(action=d.action)
            self.actions_total += 1
            try:
                outcome = self._dispatch(d)
                self.ledger.record(
                    "action_outcome", action=d.action, target=d.target,
                    rule=d.rule, ok=True,
                    outcome={k: outcome[k] for k in list(outcome)[:6]})
                if self.logger is not None:
                    self.logger.info(
                        "control: %s on %s (%s)", d.action, d.target, d.rule)
            except LeverError as e:
                self.ledger.record(
                    "action_outcome", action=d.action, target=d.target,
                    rule=d.rule, ok=False, error=str(e)[:200])
                if self.logger is not None:
                    self.logger.warning(
                        "control: %s on %s FAILED: %s",
                        d.action, d.target, e)

    def _journal_suppressed(self) -> None:
        for s in self.engine.drain_suppressed():
            self._suppressed_c.inc(reason=s.get("reason", ""))
            if s.get("reason") == "budget" and s.pop("first", False):
                self.ledger.record("budget_exhausted", **s)
            else:
                s.pop("first", None)
                self.ledger.record("action_suppressed", **s)

    # ------------------------------------------------------------------
    # canary protocol
    # ------------------------------------------------------------------
    def _canary_tick(self, canary_signals: Optional[dict]) -> None:
        if self.canary_replica is None:
            return
        cp = self.policy.canary
        st = self._canary
        if st.phase == "idle":
            head = log_next_seq(self.canary_log_path)
            if head <= self._canary_next:
                return
            st.phase = "settling"
            st.wave_start, st.wave_end = self._canary_next, head
            st.settle_left = max(1, cp.settle_ticks)
            st.probes = []
            st.records = pending_records(
                self.canary_log_path, start_seq=st.wave_start,
                end_seq=st.wave_end)
            self.ledger.record(
                "canary_soak_begin", target=self.canary_replica.url,
                wave_start=st.wave_start, wave_end=st.wave_end,
                deltas=sum(1 for r in st.records if r.delta is not None))
            return
        if st.phase == "settling":
            applied = None
            if canary_signals is not None:
                applied = canary_signals.get("_replication_watermark")
            # seq_watermark is the LAST APPLIED log seq; the wave covers
            # [wave_start, wave_end), so the canary has the whole wave
            # once the watermark reaches wave_end - 1.
            if applied is not None and int(applied) >= st.wave_end - 1:
                st.phase = "soaking"
            else:
                st.settle_left -= 1
                if st.settle_left <= 0:
                    # Settle window exhausted: an unobservable canary must
                    # not gate the fleet forever, and a reachable canary
                    # whose watermark never reaches the wave (tailer stuck
                    # or refusing the delta) is itself evidence the wave is
                    # bad. Either way the wave must not promote.
                    self._canary_verdict(
                        False,
                        reason=("canary_unreachable" if applied is None
                                else "canary_stalled"))
                return
        if st.phase != "soaking":
            return
        probe = self._canary_probe(canary_signals)
        st.probes.append(probe)
        self.ledger.record(
            "canary_probe", target=self.canary_replica.url,
            wave_start=st.wave_start, wave_end=st.wave_end, **probe)
        if probe.get("breach"):
            self._canary_verdict(False, reason=probe["breach"])
            return
        if len(st.probes) >= cp.soak_ticks:
            self._canary_verdict(True, reason="soak_complete")

    def _canary_probe(self, canary_signals: Optional[dict]) -> dict:
        """One soak observation: drift vs reference + latency/error gate."""
        cp = self.policy.canary
        out: dict = {"drift": None, "canary_latency_ms": None}
        if canary_signals is None:
            out["breach"] = "canary_unreachable"
            return out
        lat = canary_signals.get("probe_latency_ms")
        out["canary_latency_ms"] = lat
        if lat is not None and lat > cp.max_probe_latency_ms:
            out["breach"] = "canary_latency"
            return out
        if "replication_error" in (canary_signals.get("_degraded") or []):
            out["breach"] = "canary_replication_error"
            return out
        if self.probe_rows and self.reference_replica is not None:
            try:
                _, c = self.levers.score(
                    self.canary_replica.url, self.probe_rows)
                _, r = self.levers.score(
                    self.reference_replica.url, self.probe_rows)
                cs = [float(s) for s in c.get("scores") or []]
                rs = [float(s) for s in r.get("scores") or []]
                if cs and len(cs) == len(rs):
                    drift = sum(
                        abs(a - b) for a, b in zip(cs, rs)) / len(cs)
                    out["drift"] = round(drift, 6)
                    if drift > cp.drift_threshold:
                        out["breach"] = "score_drift"
            except LeverError as e:
                out["breach"] = f"probe_error:{str(e)[:120]}"
        return out

    def _canary_verdict(self, promote: bool, reason: str) -> None:
        st = self._canary
        canary = self.canary_replica
        assert canary is not None
        self._verdicts_c.inc(
            verdict="promote" if promote else "rollback")
        if promote:
            seqs = promote_wave(self._main_writer, st.records)
            self.ledger.record(
                "canary_promote", target=canary.url, reason=reason,
                wave_start=st.wave_start, wave_end=st.wave_end,
                main_seqs=seqs, probes=len(st.probes))
            if self.logger is not None:
                self.logger.info(
                    "canary wave [%d,%d) promoted -> main seqs %s",
                    st.wave_start, st.wave_end, seqs)
        else:
            self.ledger.record(
                "canary_rollback", target=canary.url, reason=reason,
                wave_start=st.wave_start, wave_end=st.wave_end,
                probes=len(st.probes))
            if self.logger is not None:
                self.logger.warning(
                    "canary wave [%d,%d) ROLLED BACK (%s)",
                    st.wave_start, st.wave_end, reason)
            try:
                self.levers.standby_swap(canary.url, self.base_model_dir)
                resynced = self._resync_canary(canary.url)
                self.ledger.record(
                    "canary_resync", target=canary.url, ok=True,
                    deltas=resynced)
            except LeverError as e:
                self.ledger.record(
                    "canary_resync", target=canary.url, ok=False,
                    error=str(e)[:200])
        self._canary_next = st.wave_end
        st.phase = "idle"
        st.probes = []
        st.records = []

    def _resync_canary(self, url: str) -> int:
        """Re-feed the promoted mainline deltas to the rolled-back canary.

        The swap built a fresh version from the base model dir, dropping
        BOTH the poisoned wave and every previously promoted delta; the
        mainline log is the durable record of the latter, so replaying it
        over HTTP restores the canary to exactly the fleet's state. No
        idempotency keys here: these ARE intentional re-applications."""
        n = 0
        for rec in iter_log(self.main_log_path, start_seq=0):
            if rec.delta is None:
                continue
            self.levers.post_patch(url, rec.delta.to_wire(),
                                   trace_id=rec.trace_id)
            n += 1
        return n

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def tick(self) -> dict:
        """One observe→decide→actuate→journal pass. Returns a summary the
        driver logs (and tests assert on)."""
        self.ticks += 1
        self._ticks_c.inc()
        summary: dict = {"tick": self.ticks, "decisions": 0}
        canary_signals: Optional[dict] = None
        for target in self.replicas:
            signals = self._observe(target)
            if target.canary:
                canary_signals = signals
            if signals is None:
                continue
            series_signals = {
                k: v for k, v in signals.items() if not k.startswith("_")}
            self.engine.observe(target.url, series_signals)
            # The canary soaks in isolation: anomaly rules still watch it
            # (a dead tailer on the canary matters) but the autoscaler
            # only tunes traffic-bearing replicas.
            context = {
                "max_batch": None if target.canary
                else signals.get("_max_batch"),
                "max_queue": signals.get("_max_queue"),
            }
            decisions = self.engine.decide(target.url, context)
            if decisions:
                self.ledger.record(
                    "observation", target=target.url, **{
                        k: (round(v, 4) if isinstance(v, float) else v)
                        for k, v in series_signals.items()})
            self._actuate(decisions)
            summary["decisions"] += len(decisions)
        self._journal_suppressed()
        self._canary_tick(canary_signals)
        summary["canary_phase"] = self._canary.phase
        return summary

    def run(self, max_ticks: Optional[int] = None,
            stop: Optional[threading.Event] = None) -> dict:
        stop = stop or self._stop
        self.ledger.record(
            "controller_started", policy_digest=self.policy.digest(),
            tick_s=self.policy.tick_s,
            replicas=[r.url for r in self.replicas],
            canary=(self.canary_replica.url
                    if self.canary_replica else None))
        try:
            while not stop.is_set():
                t0 = time.monotonic()
                self.tick()
                if max_ticks is not None and self.ticks >= max_ticks:
                    break
                elapsed = time.monotonic() - t0
                stop.wait(max(0.0, self.policy.tick_s - elapsed))
        finally:
            self.ledger.record(
                "controller_stopped", ticks=self.ticks,
                actions=self.actions_total)
            if self._main_writer is not None:
                self._main_writer.close()
        return {"ticks": self.ticks}

    def start(self, max_ticks: Optional[int] = None) -> None:
        self._thread = threading.Thread(
            target=self.run, kwargs={"max_ticks": max_ticks},
            name="photon-control", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
