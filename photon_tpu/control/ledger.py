"""Journaled control decisions — ``control-ledger.jsonl`` (docs/control.md).

The control plane's whole value is auditability: a loop that silently
actuates levers is indistinguishable from flakiness. Every decision the
controller takes — observation, the rule that matched, the action fired,
and the action's outcome — lands as one row here, under the SAME row
contract as the supervisor's :class:`~photon_tpu.supervisor.RecoveryJournal`
(PR 15): ``{"time": <ISO-8601 UTC>, "t": <sub-second wall stamp>,
"event": <name>, "pid": ..., **fields}``, one unbuffered whole-line
O_APPEND write per row, mirrored as a ``control.<event>`` trace instant so
the chaos drill's ledger and timeline tell one story. The shared contract
is what lets ``obs/fleet.merge_journals`` interleave control rows with
recovery rows causally and the fleet report render a "## Control" section
without a second parser.

Event vocabulary (the closed set the report counts; see docs/control.md):

=============================  =========================================
event                          meaning
=============================  =========================================
``controller_started``         loop came up (policy digest in fields)
``controller_stopped``         loop exited (ticks, actions totals)
``observation``                one tick's per-target signal snapshot
                               (only journaled when a rule fired or
                               ``verbose`` — observations are high-rate)
``rule_fired``                 a policy rule's predicate latched
``action``                     a lever actuated (action, target, params)
``action_outcome``             the lever's reply (ok/error + detail)
``action_suppressed``          predicate held but cooldown/budget vetoed
``budget_exhausted``           a rule ran out of budget (journaled once)
``canary_soak_begin``          new canary wave entered soak
``canary_probe``               one soak drift probe (drift, latencies)
``canary_promote``             wave promoted into the main delta log
``canary_rollback``            wave rejected; canary reset to base
``canary_resync``              canary re-fed the promoted mainline state
=============================  =========================================
"""
from __future__ import annotations

import json
import os
import time
from typing import Iterator, Optional

__all__ = ["ControlLedger", "LEDGER_FILENAME", "read_ledger"]

# fleet.discover keys on this name (family: control_ledgers); keep the
# two in sync or the report loses the Control section.
LEDGER_FILENAME = "control-ledger.jsonl"


class ControlLedger:
    """Append-only JSONL record of control-plane decisions.

    Mirrors :class:`photon_tpu.supervisor.RecoveryJournal` byte-for-byte in
    row shape (``time``/``t``/``event``/``pid``) because the fleet journal
    merger and the report's ledger counters are shared between the two —
    the control plane buys its observability by speaking the existing
    contract, not by inventing one. Writes are best-effort: the ledger is
    evidence, never a new failure mode."""

    def __init__(self, path: str):
        self.path = path

    def record(self, event: str, _mirror: bool = True, **fields) -> None:
        """Append one row; ``_mirror=False`` skips the trace instant for
        events whose canonical timeline instant is emitted elsewhere."""
        from photon_tpu.obs import instant
        from photon_tpu.utils import write_metrics_jsonl

        row = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            # Sub-second stamp: merge_journals orders control rows against
            # recovery rows from other processes; the ISO second alone
            # cannot sequence an action against the restart it requested.
            "t": round(time.time(), 6),
            "event": event,
            "pid": os.getpid(),
            **fields,
        }
        try:
            write_metrics_jsonl(self.path, [row])
        except OSError:
            pass  # evidence, never a failure mode
        if _mirror:
            instant(f"control.{event}", cat="control", **fields)

    def rows(self) -> list[dict]:
        """All rows currently on disk (tests / smoke audits)."""
        return list(read_ledger(self.path))


def read_ledger(path: str) -> Iterator[dict]:
    """Yield ledger rows; tolerates a torn trailing line (a reader racing
    the writer sees whole lines only, but a crashed writer may leave one)."""
    try:
        f = open(path, "r", encoding="utf-8")
    except OSError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue
