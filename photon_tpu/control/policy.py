"""Declarative control policies + the damped decision engine.

The policy layer answers one question per tick, per target: *given the
observed signal series, which lever (if any) fires now?* — and answers it
with three structural damping guarantees (docs/control.md) instead of
tuning folklore:

1. **Hysteresis bands.** A rule acts only when its signal sits beyond a
   threshold for ``min_run`` consecutive ticks, and the opposite action
   needs the signal beyond a *different* (lower/higher) threshold — a
   single noisy sample can never flap a lever, because the band between
   ``low`` and ``high`` is a dead zone by construction.
2. **Per-lever cooldown.** Cooldowns are keyed by ``(lever, target)`` and
   shared by BOTH directions of a lever, so a reversal within the cooldown
   window is structurally impossible, not merely unlikely — the property
   the chaos drill asserts from the ledger.
3. **Budgets.** Every rule carries an action budget for the run; an
   exhausted budget suppresses the rule (journaled once), bounding the
   worst case of a pathological signal at a constant number of actions.

The engine itself (:class:`PolicyEngine`) is pure observation-in /
decision-out: no HTTP, no threads, injectable clock — the controller owns
actuation, the engine owns restraint, and tests drive the engine with
synthetic series to prove the damping claims without a fleet.

Level-shift detection reuses the fleet report's
:func:`~photon_tpu.obs.analysis.report.robust_scores` /
``detect_level_shifts`` detector (PR 15) on the controller's OWN per-tick
probe latencies — the serving ``/metrics`` histogram is lifetime-
cumulative, so an 8× shift would take thousands of samples to move its
p95, while the probe series shifts on the very next tick.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from collections import deque
from typing import Callable, Optional, Sequence

from photon_tpu.obs.analysis.report import detect_level_shifts

__all__ = [
    "Rule",
    "CanaryPolicy",
    "AutoscalePolicy",
    "ControlPolicy",
    "Decision",
    "PolicyEngine",
]

# Signals the engine understands (observation dict keys). The controller
# populates what it can each tick; rules referencing an absent signal
# simply do not fire that tick.
KNOWN_SIGNALS = (
    "probe_latency_ms",   # controller's own /score round-trip this tick
    "latency_p95_ms",     # server-reported lifetime p95 (context only)
    "memory_watermark",   # device-memory high-water fraction [0, 1]
    "tailer_dead",        # 1.0 when healthz says replication_tailer_dead
    "queue_frac",         # batcher queued / max_queue [0, 1]
    "errors",             # server error counter (cumulative)
)

_KINDS = ("level_shift", "threshold", "flag")
_ACTIONS = ("standby_swap", "shed_cache", "restart_tailer", "scale_batcher")


@dataclasses.dataclass(frozen=True)
class Rule:
    """One anomaly→action binding.

    ``kind`` selects the predicate: ``level_shift`` runs the robust
    z-score detector over the signal series; ``threshold`` requires the
    last ``min_run`` samples at/above ``high`` (and, when ``trend_ticks``
    is set, a rising trend across that many ticks — the memory rule fires
    on trajectory, before the OOM ladder would); ``flag`` requires the
    signal truthy for ``min_run`` consecutive ticks (tailer death)."""

    name: str
    signal: str
    kind: str
    action: str
    high: float = 0.0
    min_run: int = 2
    trend_ticks: int = 0
    z_threshold: float = 6.0
    window: int = 8
    min_history: int = 4
    cooldown_s: float = 30.0
    budget: Optional[int] = 3

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if self.action not in _ACTIONS:
            raise ValueError(f"unknown rule action {self.action!r}")
        if self.signal not in KNOWN_SIGNALS:
            raise ValueError(f"unknown rule signal {self.signal!r}")
        if self.min_run < 1:
            raise ValueError("min_run must be >= 1")

    def to_dict(self) -> dict:
        # Keep None values: budget=None means UNLIMITED and must survive a
        # JSON round-trip (dropping it would resurrect the default budget
        # and silently change the policy digest).
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Rule":
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class CanaryPolicy:
    """Canary wave verdict thresholds (docs/control.md §canary protocol).

    ``soak_ticks`` probes must pass before promotion; any single probe
    breaching ``drift_threshold`` (mean |canary − reference| score delta)
    or ``max_probe_latency_ms`` rolls the wave back immediately — a
    poisoned delta should not get to finish its soak."""

    soak_ticks: int = 3
    drift_threshold: float = 0.25
    max_probe_latency_ms: float = 2000.0
    settle_ticks: int = 2  # ticks to wait for the canary to apply a wave

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "CanaryPolicy":
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Damped micro-batcher sizing from the measured saturation point.

    Scale UP (``max_batch`` ×2, queue re-derived) only when admission
    pressure is high (``queue_frac >= queue_high`` for ``min_run`` ticks)
    AND latency still has headroom below the knee — batching more when
    already past saturation would worsen the very latency the queue depth
    is complaining about. Scale DOWN (÷2) only when latency sits above the
    knee while the queue is shallow (``queue_frac <= queue_low``) — the
    batch itself is the bottleneck. Between the bands: do nothing. Both
    directions share one ``(scale_batcher, target)`` cooldown."""

    queue_high: float = 0.75
    queue_low: float = 0.25
    knee_latency_ms: float = 250.0
    min_run: int = 2
    max_batch_floor: int = 8
    max_batch_ceiling: int = 4096
    queue_per_batch: int = 4  # max_queue follows max_batch at this ratio
    cooldown_s: float = 20.0
    budget: Optional[int] = 6

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "AutoscalePolicy":
        return cls(**d)


def _default_rules() -> tuple:
    return (
        # 8× latency level shift ⇒ pre-warm standby + swap (PR 12 lever).
        Rule(name="latency_shift", signal="probe_latency_ms",
             kind="level_shift", action="standby_swap",
             z_threshold=6.0, window=8, min_history=4, min_run=2,
             cooldown_s=30.0, budget=2),
        # Memory watermark trend ⇒ proactive shed before the OOM ladder.
        Rule(name="memory_trend", signal="memory_watermark",
             kind="threshold", action="shed_cache",
             high=0.75, min_run=2, trend_ticks=3,
             cooldown_s=15.0, budget=4),
        # Dead replication tailer ⇒ journaled restart request, budgeted
        # like the supervisor's own restart policy (max_restarts).
        Rule(name="tailer_dead", signal="tailer_dead",
             kind="flag", action="restart_tailer",
             min_run=2, cooldown_s=10.0, budget=3),
    )


@dataclasses.dataclass(frozen=True)
class ControlPolicy:
    """The whole declarative policy: tick cadence + three rule families.

    JSON round-trips (``to_json``/``from_file``) so the control driver can
    run an operator-authored policy via ``--policy``; :meth:`digest` stamps
    the ledger's ``controller_started`` row so a drill's decisions are
    attributable to the exact policy that made them."""

    tick_s: float = 1.0
    rules: Sequence[Rule] = dataclasses.field(default_factory=_default_rules)
    canary: CanaryPolicy = dataclasses.field(default_factory=CanaryPolicy)
    autoscale: Optional[AutoscalePolicy] = dataclasses.field(
        default_factory=AutoscalePolicy)
    max_actions_per_tick: int = 4

    def __post_init__(self):
        object.__setattr__(self, "rules", tuple(self.rules))
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {names}")

    def to_dict(self) -> dict:
        return {
            "tick_s": self.tick_s,
            "max_actions_per_tick": self.max_actions_per_tick,
            "rules": [r.to_dict() for r in self.rules],
            "canary": self.canary.to_dict(),
            "autoscale": (None if self.autoscale is None
                          else self.autoscale.to_dict()),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def digest(self) -> str:
        return hashlib.sha256(
            json.dumps(self.to_dict(), sort_keys=True).encode()
        ).hexdigest()[:12]

    @classmethod
    def from_dict(cls, d: dict) -> "ControlPolicy":
        kw: dict = {}
        if "tick_s" in d:
            kw["tick_s"] = float(d["tick_s"])
        if "max_actions_per_tick" in d:
            kw["max_actions_per_tick"] = int(d["max_actions_per_tick"])
        if "rules" in d:
            kw["rules"] = tuple(Rule.from_dict(r) for r in d["rules"])
        if "canary" in d:
            kw["canary"] = CanaryPolicy.from_dict(d["canary"])
        if "autoscale" in d:
            kw["autoscale"] = (None if d["autoscale"] is None
                               else AutoscalePolicy.from_dict(d["autoscale"]))
        return cls(**kw)

    @classmethod
    def from_json(cls, text: str) -> "ControlPolicy":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_file(cls, path: str) -> "ControlPolicy":
        with open(path) as f:
            return cls.from_json(f.read())


@dataclasses.dataclass(frozen=True)
class Decision:
    """One actuation the engine is asking the controller to perform."""

    rule: str
    action: str
    target: str
    params: dict
    evidence: dict


class _RuleState:
    __slots__ = ("spent", "budget_logged")

    def __init__(self):
        self.spent = 0
        self.budget_logged = False


class PolicyEngine:
    """Observation-in / decision-out evaluator with the damping state.

    Feed one :meth:`observe` per (tick, target) and collect decisions.
    ``clock`` is injectable (monotonic seconds) so tests prove cooldown
    semantics without sleeping."""

    def __init__(self, policy: ControlPolicy,
                 clock: Optional[Callable[[], float]] = None):
        import time as _time

        self.policy = policy
        self._clock = clock or _time.monotonic
        # (signal, target) -> series of samples, newest last. Window keeps
        # level-shift history plus slack; deque bounds memory for days-long
        # loops.
        self._series: dict[tuple[str, str], deque] = {}
        # (lever, target) -> monotonic stamp of the last actuation. Keyed
        # by LEVER, not rule/direction — the no-reversal-in-cooldown
        # guarantee lives here.
        self._cooldowns: dict[tuple[str, str], float] = {}
        self._rule_state: dict[str, _RuleState] = {}
        self.suppressed: list[dict] = []   # drained by the controller

    # -- observation intake ------------------------------------------------
    def observe(self, target: str, signals: dict) -> None:
        for name, value in signals.items():
            if value is None:
                continue
            key = (name, target)
            series = self._series.get(key)
            if series is None:
                depth = 4 * max(
                    [r.window for r in self.policy.rules] or [8]) + 8
                series = self._series[key] = deque(maxlen=depth)
            series.append(float(value))

    def series(self, signal: str, target: str) -> list[float]:
        return list(self._series.get((signal, target), ()))

    # -- damping primitives ------------------------------------------------
    def _cooldown_remaining(self, lever: str, target: str,
                            cooldown_s: float) -> float:
        stamp = self._cooldowns.get((lever, target))
        if stamp is None:
            return 0.0
        return max(0.0, cooldown_s - (self._clock() - stamp))

    def _note_actuated(self, lever: str, target: str) -> None:
        self._cooldowns[(lever, target)] = self._clock()

    def _admit(self, rule_name: str, lever: str, target: str,
               cooldown_s: float, budget: Optional[int],
               evidence: dict) -> bool:
        """Cooldown + budget gate; False records a suppression."""
        state = self._rule_state.setdefault(rule_name, _RuleState())
        remaining = self._cooldown_remaining(lever, target, cooldown_s)
        if remaining > 0:
            self.suppressed.append({
                "rule": rule_name, "target": target, "reason": "cooldown",
                "cooldown_remaining_s": round(remaining, 3), **evidence})
            return False
        if budget is not None and state.spent >= budget:
            self.suppressed.append({
                "rule": rule_name, "target": target, "reason": "budget",
                "budget": budget, "first": not state.budget_logged,
                **evidence})
            state.budget_logged = True
            return False
        state.spent += 1
        self._note_actuated(lever, target)
        return True

    # -- predicates --------------------------------------------------------
    def _predicate(self, rule: Rule, target: str) -> Optional[dict]:
        """Evidence dict when the rule's condition holds NOW, else None."""
        series = self.series(rule.signal, target)
        if not series:
            return None
        if rule.kind == "flag":
            tail = series[-rule.min_run:]
            if len(tail) >= rule.min_run and all(v >= 1.0 for v in tail):
                return {"signal": rule.signal, "run": len(tail)}
            return None
        if rule.kind == "threshold":
            tail = series[-rule.min_run:]
            if len(tail) < rule.min_run or not all(
                    v >= rule.high for v in tail):
                return None
            if rule.trend_ticks > 1:
                trend = series[-rule.trend_ticks:]
                if len(trend) < rule.trend_ticks or trend[-1] <= trend[0]:
                    return None  # level high but not rising: not a ramp
            return {"signal": rule.signal, "value": series[-1],
                    "high": rule.high}
        # level_shift: shift must be live at the series edge — a shift that
        # detected ticks ago and re-baselined is history, not a condition.
        shifts = detect_level_shifts(
            series, window=rule.window, z_threshold=rule.z_threshold,
            min_history=rule.min_history, min_run=rule.min_run)
        live = [s for s in shifts if s["index"] == len(series) - 1]
        if not live:
            return None
        s = live[0]
        return {"signal": rule.signal, "value": s["value"],
                "median": s["median"], "z": s["z"]}

    # -- evaluation --------------------------------------------------------
    def decide(self, target: str, signals: dict) -> list[Decision]:
        """Evaluate every rule family for ``target`` this tick.

        ``signals`` carries tick-scoped context the series don't (current
        ``max_batch``/``max_queue`` for the autoscaler)."""
        decisions: list[Decision] = []
        for rule in self.policy.rules:
            evidence = self._predicate(rule, target)
            if evidence is None:
                continue
            if not self._admit(rule.name, rule.action, target,
                               rule.cooldown_s, rule.budget, evidence):
                continue
            params: dict = {}
            decisions.append(Decision(
                rule=rule.name, action=rule.action, target=target,
                params=params, evidence=evidence))
        auto = self._decide_autoscale(target, signals)
        if auto is not None:
            decisions.append(auto)
        return decisions[: self.policy.max_actions_per_tick]

    def _decide_autoscale(self, target: str,
                          signals: dict) -> Optional[Decision]:
        ap = self.policy.autoscale
        if ap is None:
            return None
        max_batch = signals.get("max_batch")
        if not max_batch:
            return None
        queue = self.series("queue_frac", target)
        lat = self.series("probe_latency_ms", target)
        if len(queue) < ap.min_run or len(lat) < ap.min_run:
            return None
        q_tail = queue[-ap.min_run:]
        l_tail = lat[-ap.min_run:]
        max_batch = int(max_batch)
        new_batch = None
        direction = None
        if (all(q >= ap.queue_high for q in q_tail)
                and all(l < ap.knee_latency_ms for l in l_tail)
                and max_batch < ap.max_batch_ceiling):
            new_batch = min(max_batch * 2, ap.max_batch_ceiling)
            direction = "up"
        elif (all(q <= ap.queue_low for q in q_tail)
                and all(l >= ap.knee_latency_ms for l in l_tail)
                and max_batch > ap.max_batch_floor):
            new_batch = max(max_batch // 2, ap.max_batch_floor)
            direction = "down"
        if new_batch is None or new_batch == max_batch:
            return None
        evidence = {
            "queue_frac": q_tail[-1], "probe_latency_ms": l_tail[-1],
            "direction": direction, "max_batch": max_batch,
        }
        if not self._admit("autoscale", "scale_batcher", target,
                           ap.cooldown_s, ap.budget, evidence):
            return None
        new_queue = new_batch * ap.queue_per_batch
        return Decision(
            rule="autoscale", action="scale_batcher", target=target,
            params={"max_batch": new_batch, "max_queue": new_queue},
            evidence=evidence)

    def drain_suppressed(self) -> list[dict]:
        out, self.suppressed = self.suppressed, []
        return out
