"""Streaming fleet view: live telemetry aggregation + ``GET /fleet``.

PR 15's fleet layer is post-hoc by construction — shards merge and the
run report builds after every process has exited. This module is the
same aggregation run *at the live edge* (docs/observability.md §"Live
fleet view"):

* :class:`LiveFleetWatcher` tails a ``--telemetry-dir`` on an interval:
  registry shards re-merge idempotently (per-``shard_id`` delta fold, so
  a replica's periodic re-export never double-counts), metrics JSONL
  histories are tailed incrementally by byte offset (torn tails from a
  live writer are left for the next tick), and recovery/patch journals +
  control ledgers are re-read for the fleet story.
* :class:`StreamingDetector` is the PR 15 median/MAD level-shift
  detector restated as an online fold: each new point is scored against
  the trailing window of its predecessors (the point itself excluded),
  and a run of ``min_run`` consecutive over-threshold points flags —
  the SAME points the batch ``detect_level_shifts`` would flag, but
  available while the fleet is still running.
* :class:`LiveFleetServer` is a jax-free stdlib HTTP front end
  (``cli/obs_driver.py``): ``GET /fleet`` returns the continuously
  refreshed JSON state (``?format=md`` renders the run report as
  markdown), ``GET /healthz`` liveness, ``GET /metrics`` the folded
  fleet registry.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from photon_tpu.obs.analysis.report import (
    DEFAULT_ANOMALY_METRICS,
    DEFAULT_MIN_HISTORY,
    DEFAULT_MIN_RUN,
    DEFAULT_WINDOW,
    DEFAULT_Z,
    _MAD_SCALE,
    _median,
    build_report,
    format_markdown,
)
from photon_tpu.obs.metrics import MetricsRegistry

__all__ = [
    "LIVE_SCHEMA",
    "StreamingDetector",
    "LiveFleetWatcher",
    "LiveFleetServer",
]

LIVE_SCHEMA = "photon-fleet-live/1"


class StreamingDetector:
    """The median/MAD level-shift detector as an online fold.

    Semantics match ``report.detect_level_shifts`` point-for-point: a
    point's robust z is measured against the trailing ``window``
    predecessors (itself excluded; fewer than ``min_history``
    predecessors → no score), over-threshold points accumulate into a
    run, and the run flags once it reaches ``min_run`` — first the
    buffered run points (so batch and streaming flag the SAME indices),
    then every further point while the run continues.
    """

    def __init__(self, window: int = DEFAULT_WINDOW,
                 z_threshold: float = DEFAULT_Z,
                 min_history: int = DEFAULT_MIN_HISTORY,
                 min_run: int = DEFAULT_MIN_RUN):
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.min_history = max(1, int(min_history))
        self.min_run = max(1, int(min_run))
        self._hist: deque = deque(maxlen=self.window)
        self._run: list[dict] = []
        self.points = 0
        self.anomalies: list[dict] = []

    def push(self, value: float) -> list[dict]:
        """Fold one new point; returns the rows flagged BY this point
        (empty for quiet points), each ``{"index","value","median","z"}``."""
        x = float(value)
        idx = self.points
        self.points += 1
        z = None
        med = None
        if len(self._hist) >= self.min_history:
            hist = list(self._hist)
            med = _median(hist)
            mad = _median([abs(h - med) for h in hist])
            scale = _MAD_SCALE * mad
            if scale <= 0:
                scale = max(abs(med) * 0.05, 1e-9)
            z = abs(x - med) / scale
        flagged: list[dict] = []
        if z is not None and z >= self.z_threshold:
            self._run.append({
                "index": idx,
                "value": round(x, 6),
                "median": round(med, 6),
                "z": round(z, 3),
            })
            if len(self._run) == self.min_run:
                flagged = list(self._run)
            elif len(self._run) > self.min_run:
                flagged = [self._run[-1]]
        else:
            self._run = []
        self._hist.append(x)
        if flagged:
            self.anomalies.extend(flagged)
        return flagged


class _JsonlTail:
    """Incremental reader of one JSONL file: remembers the byte offset
    of the last COMPLETE line consumed, so a live writer's torn tail is
    simply re-read whole on the next tick. A shrunken file (truncate /
    rewrite) resets the offset — re-reading beats silently skipping."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0

    def read_new(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.offset:
            self.offset = 0
        if size == self.offset:
            return []
        rows: list[dict] = []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                chunk = f.read(size - self.offset)
        except OSError:
            return []
        # Only complete lines advance the offset; a partial tail waits
        # for its newline.
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        complete, self.offset = chunk[:end + 1], self.offset + end + 1
        for line in complete.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue  # torn or corrupt row: skip, loudly counted upstream
            if isinstance(row, dict):
                rows.append(row)
        return rows


class LiveFleetWatcher:
    """Tail one telemetry dir; fold every tick into a live fleet state."""

    def __init__(
        self,
        run_dir: str,
        metrics: Optional[Sequence[str]] = None,
        window: int = DEFAULT_WINDOW,
        z_threshold: float = DEFAULT_Z,
        min_history: int = DEFAULT_MIN_HISTORY,
        min_run: int = DEFAULT_MIN_RUN,
        report_top: int = 5,
    ):
        self.run_dir = os.path.abspath(run_dir)
        self.watch_metrics = tuple(metrics or DEFAULT_ANOMALY_METRICS)
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.min_history = int(min_history)
        self.min_run = int(min_run)
        self.report_top = int(report_top)
        self._lock = threading.Lock()
        # Persistent fold target: collect_shards' per-shard_id delta
        # merge makes re-collection of a re-exported shard idempotent.
        self.registry = MetricsRegistry()
        self._tails: dict[str, _JsonlTail] = {}
        # (file, metric) -> detector, state carried across ticks — the
        # "streaming at the live edge" part.
        self._detectors: dict[tuple, StreamingDetector] = {}
        self._shard_meta: dict[str, dict] = {}
        self.ticks = 0
        self.last_tick_at: Optional[float] = None
        self.last_error: Optional[str] = None
        self._state: dict = {"schema": LIVE_SCHEMA,
                             "telemetry_dir": self.run_dir,
                             "ticks": 0, "roles": [],
                             "live_anomalies": [],
                             "n_live_anomalies": 0}
        self._markdown = "(no tick yet)\n"

    # ---------------------------------------------------------------- tick

    def tick(self) -> dict:
        """One refresh: discover artifacts, fold new evidence, rebuild
        the run report. Never raises — the watcher outlives any single
        bad artifact (the error lands in the payload instead)."""
        from photon_tpu.obs import fleet

        t0 = time.time()
        try:
            state = self._tick_inner(fleet)
            self.last_error = None
        except Exception as e:  # noqa: BLE001 - the watcher must outlive a bad tick
            self.last_error = f"{type(e).__name__}: {e}"
            with self._lock:
                state = dict(self._state)
                state["last_error"] = self.last_error
                self._state = state
            return state
        self.ticks += 1
        self.last_tick_at = t0
        state["ticks"] = self.ticks
        state["last_tick_at"] = t0
        state["tick_seconds"] = round(time.time() - t0, 4)
        with self._lock:
            self._state = state
        return state

    def _tick_inner(self, fleet) -> dict:
        files = fleet.discover(self.run_dir)

        # Registry shards: idempotent incremental re-merge into the
        # persistent registry; shard metadata feeds the live role list.
        # Per-shard isolation: one torn/corrupt shard (a writer mid-crash)
        # must not blind the view to every healthy role.
        shard_warnings: list[str] = []
        for path in files.registry_shards:
            try:
                _, metas = fleet.collect_shards([path],
                                                registry=self.registry)
            except fleet.FleetMergeError as e:
                shard_warnings.append(str(e))
                continue
            for m in metas:
                self._shard_meta[m.get("shard_id") or m.get("path")] = {
                    "shard_id": m.get("shard_id"),
                    "role": m.get("role"),
                    "pid": m.get("pid"),
                    "anchor": m.get("anchor"),
                    "path": m.get("path"),
                }

        # Metrics JSONL: tail new rows into the streaming detectors.
        from photon_tpu.obs.analysis.artifacts import flatten_metrics

        live_anoms: list[dict] = []
        new_points = 0
        for path in files.metrics_jsonl:
            tail = self._tails.get(path)
            if tail is None:
                tail = self._tails[path] = _JsonlTail(path)
            for row in tail.read_new():
                flat = flatten_metrics(row)
                for metric in self.watch_metrics:
                    v = flat.get(metric)
                    if v is None:
                        continue
                    key = (path, metric)
                    det = self._detectors.get(key)
                    if det is None:
                        det = self._detectors[key] = StreamingDetector(
                            window=self.window,
                            z_threshold=self.z_threshold,
                            min_history=self.min_history,
                            min_run=self.min_run)
                    new_points += 1
                    for row_flagged in det.push(v):
                        live_anoms.append({
                            "file": os.path.relpath(path, self.run_dir),
                            "metric": metric,
                            **row_flagged,
                        })

        # Full run report (the PR 15 batch view) rebuilt per tick: traces
        # and journals are small while a run is live, and the payload
        # contract says "the run report, continuously refreshed". Best
        # effort — a single corrupt artifact degrades to the previous
        # tick's report plus a warning, not a dead /fleet.
        try:
            report = build_report(
                self.run_dir, metrics=self.watch_metrics,
                window=self.window, z_threshold=self.z_threshold,
                min_run=self.min_run, top=self.report_top)
        except Exception as e:  # noqa: BLE001 - keep serving the live view
            shard_warnings.append(f"report: {type(e).__name__}: {e}")
            report = self._state.get("report") or {}

        detectors = [{
            "file": os.path.relpath(path, self.run_dir),
            "metric": metric,
            "points": det.points,
            "anomalies": det.anomalies[-self.report_top:],
            "n_anomalies": len(det.anomalies),
        } for (path, metric), det in sorted(self._detectors.items())]
        n_live = sum(d["n_anomalies"] for d in detectors)

        roles = sorted({m["role"] for m in self._shard_meta.values()
                        if m.get("role")})
        state = {
            "schema": LIVE_SCHEMA,
            "telemetry_dir": self.run_dir,
            "roles": roles,
            "registry_shards": sorted(
                self._shard_meta.values(),
                key=lambda m: (m.get("role") or "", m.get("pid") or 0)),
            "sources": {
                "registry_shards": len(files.registry_shards),
                "metrics_jsonl": [os.path.relpath(p, self.run_dir)
                                  for p in files.metrics_jsonl],
                "traces": len(files.traces),
                "journals": len(files.journals),
                "patch_journals": len(files.patch_journals),
                "control_ledgers": len(files.control_ledgers),
            },
            "detector": {
                "window": self.window,
                "z_threshold": self.z_threshold,
                "min_history": self.min_history,
                "min_run": self.min_run,
                "metrics": list(self.watch_metrics),
                "new_points_this_tick": new_points,
            },
            "streams": detectors,
            "live_anomalies_this_tick": live_anoms,
            "n_live_anomalies": n_live,
            "shard_warnings": shard_warnings,
            "registry": self.registry.snapshot(),
            "report": report,
        }
        md = ["# Live fleet view",
              "",
              f"- telemetry dir: `{self.run_dir}`",
              f"- roles (registry shards): "
              f"{', '.join(roles) if roles else '(none yet)'}",
              f"- live anomalies: {n_live}",
              ""]
        for d in detectors:
            if d["n_anomalies"]:
                md.append(f"- **{d['metric']}** in `{d['file']}`: "
                          f"{d['n_anomalies']} flagged point(s) over "
                          f"{d['points']}")
        md.append("")
        if report:
            try:
                md.append(format_markdown(report, top=self.report_top))
            except Exception as e:  # noqa: BLE001 - md is a convenience view
                md.append(f"(report render failed: {e})")
        self._markdown = "\n".join(md)
        return state

    # -------------------------------------------------------------- reads

    def state(self) -> dict:
        with self._lock:
            return dict(self._state)

    def markdown(self) -> str:
        with self._lock:
            return self._markdown


class LiveFleetServer:
    """Jax-free HTTP front end over a :class:`LiveFleetWatcher` (the
    router/control driver pattern: stdlib ``ThreadingHTTPServer``, a
    daemon tick thread, ``start``/``serve_forever``/``shutdown``)."""

    def __init__(
        self,
        run_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        interval_s: float = 2.0,
        logger=None,
        **watcher_kwargs,
    ):
        self.logger = logger
        self.interval_s = float(interval_s)
        self.watcher = LiveFleetWatcher(run_dir, **watcher_kwargs)
        self._started_at = time.time()
        live = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                if live.logger is not None:
                    live.logger.debug("obs http: " + fmt, *args)

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/fleet":
                    if "md" in query or "markdown" in query:
                        self._reply(
                            200, live.watcher.markdown().encode("utf-8"),
                            ctype="text/markdown; charset=utf-8")
                    else:
                        self._reply(200, json.dumps(
                            live.watcher.state()).encode("utf-8"))
                elif path == "/healthz":
                    w = live.watcher
                    self._reply(200 if w.ticks else 503, json.dumps({
                        "status": "ok" if w.ticks else "warming",
                        "ticks": w.ticks,
                        "last_tick_at": w.last_tick_at,
                        "last_error": w.last_error,
                        "interval_s": live.interval_s,
                        "uptime_s": round(
                            time.time() - live._started_at, 1),
                    }).encode("utf-8"))
                elif path == "/metrics":
                    if "prom" in query:
                        self._reply(
                            200,
                            live.watcher.registry.to_prometheus().encode(
                                "utf-8"),
                            ctype="text/plain; version=0.0.4; "
                                  "charset=utf-8")
                    else:
                        self._reply(200, json.dumps(
                            live.watcher.registry.snapshot()
                        ).encode("utf-8"))
                else:
                    self._reply(404, json.dumps(
                        {"error": f"no route {self.path}"}).encode("utf-8"))

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._loop_started = False
        self._serve_thread: Optional[threading.Thread] = None
        self._tick_stop = threading.Event()
        # First tick happens synchronously on the ticker thread before
        # the wait, so /healthz goes ready within one tick, not one
        # interval.
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name="photon-obs-tick", daemon=True)
        self._tick_thread.start()

    @property
    def address(self) -> tuple:
        return self.httpd.server_address[:2]

    def _tick_loop(self) -> None:
        self.watcher.tick()
        while not self._tick_stop.wait(self.interval_s):
            self.watcher.tick()

    def start(self) -> None:
        self._loop_started = True
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="photon-obs-http", daemon=True)
        self._serve_thread.start()

    def serve_forever(self) -> None:
        self._loop_started = True
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self._tick_stop.set()
        if self._loop_started:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self._tick_thread.join(timeout=5.0)
