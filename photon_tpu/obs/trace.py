"""Process-wide tracing: Chrome trace-event spans with propagated context.

Dapper-style (Sigelman et al., 2010) always-on, low-overhead tracing for the
whole stack — ingest, coordinate descent, optimizer solves, the serving
path — emitting the Chrome trace-event JSON format, so one run's timeline
opens directly in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Design constraints (docs/observability.md):

* **Near-zero cost when off.** Like ``faults.fault_point``, the hot-path
  check is one module-global read: :class:`trace_span` is a plain slotted
  class (no generator machinery) whose ``__exit__`` does nothing but two
  ``perf_counter`` reads when no collector is installed. Spans still
  measure wall-clock (``span.seconds``) so callers can keep using the
  measurement for records/logs whether or not tracing is on.
* **Propagated context.** Spans on a context-carrying thread inherit a
  ``trace_id``. A request's id is minted once at the edge
  (:func:`new_trace_id`) and attached via :func:`trace_context`; the
  serving micro-batcher stores the submitting request's id on the queue
  item, and the worker stamps it onto that row's queue-wait span and into
  the coalesced batch span's ``trace_ids`` list (a batch mixes several
  requests, so batch-level work — kernel, store resolve — correlates
  through that list rather than a single id).
* **One artifact.** Events buffer in memory (bounded) and
  :func:`stop_tracing` writes a single ``{"traceEvents": [...]}`` JSON
  object; ``scripts/obs_smoke.py`` validates the format in CI.
* **Mergeable across processes.** Every collector stamps a
  :data:`ANCHOR_EVENT` metadata instant at install — the wall-clock ↔
  ``perf_counter`` correspondence plus pid/hostname/role — so
  ``obs.fleet.merge_traces`` can align N per-process shards onto one
  wall-clock timeline (docs/observability.md §"Fleet view").

Span taxonomy (``cat`` → ``name``) is documented in docs/observability.md.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Optional

__all__ = [
    "ANCHOR_EVENT",
    "ANCHOR_SCHEMA",
    "TailSampler",
    "TraceCollector",
    "install_tail_sampler",
    "uninstall_tail_sampler",
    "tail_sampler",
    "trace_span",
    "instant",
    "process_role",
    "set_process_role",
    "start_tracing",
    "stop_tracing",
    "suspend_tracing",
    "tracing_active",
    "tracing",
    "new_trace_id",
    "current_trace_id",
    "trace_context",
]

# Common clock for all collectors in this process: microsecond timestamps
# relative to module import, so events from collectors started at different
# times still order correctly within one process.
_EPOCH = time.perf_counter()

_span_ids = itertools.count(1)
_trace_ids = itertools.count(1)
_tls = threading.local()

# Default cap on buffered events: a leaked always-on collector must not grow
# host memory without bound. Dropped events are counted and reported in the
# written artifact ("photon.trace.dropped" metadata event).
_DEFAULT_MAX_EVENTS = 1_000_000

# Default cap on the (approximate) serialized artifact size: a multi-day
# serve run with tracing on must bound disk, like PHOTON_METRICS_MAX_BYTES
# bounds the metrics JSONL. Crossing it drops further events LOUDLY — one
# "photon.trace.truncated" instant plus a log warning — never silently.
_DEFAULT_MAX_BYTES = 256 << 20

#: Per-process anchor metadata event: the wall-clock ↔ perf_counter
#: correspondence every trace shard carries so the fleet merger can align
#: clocks across processes/hosts. Stamped once at collector install.
ANCHOR_EVENT = "photon.anchor"
ANCHOR_SCHEMA = "photon-anchor/1"

# Process role stamped into the anchor (and the Perfetto process_name
# lane): "training" / "serving" / "online" / ... — set by the drivers via
# set_process_role BEFORE the collector installs.
_ROLE = os.environ.get("PHOTON_PROCESS_ROLE") or "unknown"


def set_process_role(role: str) -> None:
    """Declare this process's fleet role ("training", "serving", "online",
    ...). Call before :func:`start_tracing` — the role is stamped into the
    collector's anchor event at install and cannot retroactively rename an
    already-written shard."""
    global _ROLE
    _ROLE = str(role)


def process_role() -> str:
    return _ROLE


def _env_max_bytes() -> int:
    try:
        return int(os.environ.get("PHOTON_TRACE_MAX_BYTES",
                                  _DEFAULT_MAX_BYTES))
    except (TypeError, ValueError):
        return _DEFAULT_MAX_BYTES


def _env_sample() -> float:
    """PHOTON_TRACE_SAMPLE in (0, 1]: opt-in span sampling for long serve
    runs (1.0 = keep everything). Malformed values degrade to 1.0 — a
    typo'd knob must never kill tracing."""
    raw = os.environ.get("PHOTON_TRACE_SAMPLE")
    if not raw:
        return 1.0
    try:
        rate = float(raw)
    except (TypeError, ValueError):
        return 1.0
    if not 0.0 < rate <= 1.0:
        return 1.0
    return rate


def _approx_event_bytes(event: dict) -> int:
    """Cheap serialized-size estimate (no json.dumps on the hot path):
    fixed framing + name/cat + per-arg key and string-value lengths
    (numbers priced at a flat 12 bytes)."""
    n = 90 + len(event.get("name", "")) + len(event.get("cat", ""))
    args = event.get("args")
    if args:
        for k, v in args.items():
            n += len(k) + (len(v) if isinstance(v, str) else 12) + 6
    return n


def new_trace_id() -> str:
    """Mint a fresh trace id (process-unique, human-scannable)."""
    return f"t{os.getpid():x}.{next(_trace_ids):x}"


def current_trace_id() -> Optional[str]:
    """The trace id attached to this thread, if any."""
    return getattr(_tls, "trace_id", None)


class trace_context:
    """``with trace_context(trace_id):`` — attach a trace id to this thread.

    Used at work-handoff boundaries: the producing thread records
    ``current_trace_id()`` next to the work item, the consuming thread
    re-enters it here so spans emitted while processing the item correlate
    with the originating request. Re-entrant; restores the previous id."""

    __slots__ = ("trace_id", "_prev")

    def __init__(self, trace_id: Optional[str]):
        self.trace_id = trace_id

    def __enter__(self) -> "trace_context":
        self._prev = getattr(_tls, "trace_id", None)
        _tls.trace_id = self.trace_id
        return self

    def __exit__(self, *exc) -> None:
        _tls.trace_id = self._prev


class TraceCollector:
    """Thread-safe in-memory buffer of Chrome trace events.

    Bounds (all loud, never silent): ``max_events`` caps the buffer,
    ``max_bytes`` (env ``PHOTON_TRACE_MAX_BYTES``, default 256 MB, 0
    disables) caps the approximate serialized size — the first event over
    the cap lands one ``photon.trace.truncated`` instant plus a log
    warning, then further events drop. ``sample`` (env
    ``PHOTON_TRACE_SAMPLE``, default 1.0) keeps that fraction of SPANS —
    whole trace-id chains kept or dropped together so cross-thread /
    cross-process joins survive sampling; instants (faults, SLO verdicts,
    anchors) are never sampled out.

    The anchor metadata (``ANCHOR_EVENT`` + a Perfetto ``process_name``
    lane label) lives in :attr:`meta`, merged into the artifact at
    :meth:`to_dict` — so ``events`` stays exactly the span/instant stream.
    """

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS,
                 max_bytes: Optional[int] = None,
                 sample: Optional[float] = None):
        self.max_events = int(max_events)
        self.max_bytes = _env_max_bytes() if max_bytes is None else int(
            max_bytes)
        self.sample = _env_sample() if sample is None else float(sample)
        self.events: list[dict] = []
        self.meta: list[dict] = []
        self.dropped = 0
        self.sampled_out = 0
        self.truncated = False
        self._approx_bytes = 0
        self._span_seen = 0
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._stamp_anchor()

    def _stamp_anchor(self) -> None:
        """The fleet-merge contract (docs/observability.md §"Fleet view"):
        wall clock and perf_counter read back-to-back at install, so a
        merger can map any event's ``ts`` to wall time via
        ``anchor.wall_time + (ts - anchor.ts) / 1e6``."""
        import socket

        pc = time.perf_counter()
        wall = time.time()
        try:
            host = socket.gethostname()
        except OSError:
            host = "unknown"
        role = process_role()
        tid = threading.get_ident() & 0xFFFFFFFF
        self.meta.append({
            "name": "process_name", "ph": "M", "ts": 0,
            "pid": self._pid, "tid": 0,
            "args": {"name": f"{role}@{host} pid={self._pid}"},
        })
        anchor = {
            "name": ANCHOR_EVENT,
            "cat": "meta",
            "ph": "i",
            "s": "p",
            "ts": round((pc - _EPOCH) * 1e6, 1),
            "pid": self._pid,
            "tid": tid,
            "args": {
                "schema": ANCHOR_SCHEMA,
                "wall_time": wall,
                "perf_counter": pc,
                "pid": self._pid,
                "hostname": host,
                "role": role,
                **({"sample": self.sample} if self.sample < 1.0 else {}),
            },
        }
        self.meta.append(anchor)

    def _note_truncation(self) -> None:
        """One loud event + warning at the size cap, then silence-by-count
        (the drop counter still lands in the artifact)."""
        import logging

        self.events.append({
            "name": "photon.trace.truncated", "cat": "meta", "ph": "i",
            "s": "p",
            "ts": round((time.perf_counter() - _EPOCH) * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": {"max_bytes": self.max_bytes,
                     "events_kept": len(self.events)},
        })
        logging.getLogger("photon_tpu.obs").warning(
            "trace buffer hit PHOTON_TRACE_MAX_BYTES=%d after %d events — "
            "further events are DROPPED (counted in the artifact). Raise "
            "the cap, or set PHOTON_TRACE_SAMPLE<1 for long serve runs.",
            self.max_bytes, len(self.events),
        )

    def _keep_span(self, args: Optional[dict]) -> bool:
        """Sampling decision for one span: hash the trace id when present
        (whole request chains stay intact across threads AND processes —
        the id, not the process's counter, decides); fall back to a
        deterministic 1-in-N counter for context-free spans."""
        if self.sample >= 1.0:
            return True
        tid = (args or {}).get("trace_id")
        with self._lock:
            if tid is not None:
                keep = (zlib.crc32(str(tid).encode()) & 0xFFFF) / 65536.0 \
                    < self.sample
            else:
                self._span_seen += 1
                keep = int(self._span_seen * self.sample) != int(
                    (self._span_seen - 1) * self.sample)
            if not keep:
                self.sampled_out += 1
        return keep

    def add(self, event: dict) -> None:
        with self._lock:
            if self.truncated or len(self.events) >= self.max_events:
                self.dropped += 1
                return
            if self.max_bytes > 0:
                est = _approx_event_bytes(event)
                if self._approx_bytes + est > self.max_bytes:
                    self.truncated = True
                    self.dropped += 1
                    self._note_truncation()
                    return
                self._approx_bytes += est
            self.events.append(event)

    def complete(
        self,
        name: str,
        cat: str,
        t0: float,
        dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """One 'X' (complete) event; ``t0`` is a perf_counter value."""
        tail = _TAIL
        if tail is not None and tail.intercept(name, cat, t0, dur_s, args):
            return  # buffered; promoted into this collector only if the
            # owning request breaches the tail threshold (or errors)
        if self.sample < 1.0 and not self._keep_span(args):
            return
        self.add({
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round((t0 - _EPOCH) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": args or {},
        })

    def instant(self, name: str, cat: str, args: Optional[dict] = None) -> None:
        """One 'i' (instant) event at now — fault firings, retrace warnings."""
        self.add({
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "ts": round((time.perf_counter() - _EPOCH) * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": args or {},
        })

    def span_count(self, cat: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                1 for e in self.events
                if e["ph"] == "X" and (cat is None or e["cat"] == cat)
            )

    def to_dict(self) -> dict:
        with self._lock:
            events = self.meta + self.events
            dropped = self.dropped
            sampled_out = self.sampled_out
            truncated = self.truncated
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            out["photon.trace.dropped"] = dropped
        if sampled_out:
            out["photon.trace.sampled_out"] = sampled_out
            out["photon.trace.sample"] = self.sample
        if truncated:
            out["photon.trace.truncated_at_bytes"] = self.max_bytes
        return out

    def write(self, path: str) -> str:
        """Write the trace artifact as one JSON object (Perfetto-loadable)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path


class _BufferedSpan:
    """One buffered span event, shareable between requests: batch-level
    spans (kernel, store resolve) carry a ``trace_ids`` list and are
    buffered ONCE with the same object appended to every member request's
    buffer — the ``emitted`` flag makes promotion exactly-once however
    many members breach."""

    __slots__ = ("event", "emitted")

    def __init__(self, event: dict):
        self.event = event
        self.emitted = False


class TailSampler:
    """Tail-based trace sampling (docs/observability.md §"Tail sampling").

    Head sampling (``PHOTON_TRACE_SAMPLE``) decides before the request
    runs, so it keeps mostly boring traces; tail sampling decides AFTER:
    a bounded ring holds every in-flight request's span set cheaply
    (plain dicts, no serialization), and on completion the request is
    either promoted into the active collector — it breached the rolling
    latency threshold, or it errored — or discarded. Production traces
    then capture exactly the interesting tails, still under the
    collector's ``PHOTON_TRACE_MAX_BYTES``/``max_events`` bounds
    (promotion goes through :meth:`TraceCollector.add`).

    The rolling threshold is the ``quantile`` (default p95) of the last
    ``window`` request durations; until ``min_history`` requests have
    completed nothing is promoted on latency (errors always promote).
    Spans reach the sampler through :meth:`TraceCollector.complete` —
    any span whose ``trace_id`` (or ``trace_ids`` member) matches a
    request registered via :meth:`begin` is buffered instead of
    appended; everything else (training spans, instants, anchors) passes
    straight through. Enable via ``PHOTON_TRACE_TAIL=1`` (knobs:
    ``PHOTON_TRACE_TAIL_QUANTILE``, ``PHOTON_TRACE_TAIL_WINDOW``) or
    install one explicitly with :func:`install_tail_sampler`.
    """

    def __init__(self, capacity: int = 512, window: int = 256,
                 quantile: float = 0.95, min_history: int = 30,
                 max_spans_per_request: int = 64):
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"tail quantile must be in (0, 1): {quantile}")
        self.capacity = max(1, int(capacity))
        self.quantile = float(quantile)
        self.min_history = max(1, int(min_history))
        self.max_spans_per_request = max(1, int(max_spans_per_request))
        self._lock = threading.Lock()
        self._inflight: dict[str, list[_BufferedSpan]] = {}
        self._order: list[str] = []  # FIFO eviction order (begin() order)
        self._durations = deque(maxlen=max(self.min_history, int(window)))
        # Loud bookkeeping, surfaced via snapshot() and the promotion
        # instant — a sampler silently eating spans would be worse than
        # no sampler.
        self.promoted = 0
        self.promoted_error = 0
        self.discarded = 0
        self.evicted = 0
        self.span_overflow = 0

    # ------------------------------------------------------------- intake

    def begin(self, trace_id: str) -> None:
        """Register one in-flight request; called at the request edge
        (``ScoringServer._score``) right after the trace id is minted.
        Beyond ``capacity`` in-flight requests the OLDEST buffer is
        evicted (its spans are unrecoverable — counted, never silent)."""
        with self._lock:
            if trace_id in self._inflight:
                return
            self._inflight[trace_id] = []
            self._order.append(trace_id)
            while len(self._order) > self.capacity:
                victim = self._order.pop(0)
                if self._inflight.pop(victim, None) is not None:
                    self.evicted += 1

    def intercept(self, name: str, cat: str, t0: float, dur_s: float,
                  args: Optional[dict]) -> bool:
        """Divert one completed span into the buffers of the in-flight
        request(s) it belongs to. Returns False — pass through to the
        collector — when no owning request is registered."""
        a = args or {}
        ids = []
        tid = a.get("trace_id")
        if tid is not None:
            ids.append(tid)
        multi = a.get("trace_ids")
        if multi:
            ids.extend(multi)
        if not ids:
            return False
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round((t0 - _EPOCH) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": {**a, "span_id": next(_span_ids)},
        }
        span = _BufferedSpan(event)
        hit = False
        with self._lock:
            for t in ids:
                buf = self._inflight.get(t)
                if buf is None:
                    continue
                hit = True
                if len(buf) >= self.max_spans_per_request:
                    self.span_overflow += 1
                else:
                    buf.append(span)
        return hit

    # ---------------------------------------------------------- decision

    def _threshold_locked(self) -> Optional[float]:
        n = len(self._durations)
        if n < self.min_history:
            return None
        ordered = sorted(self._durations)
        return ordered[min(n - 1, int(self.quantile * n))]

    def threshold_s(self) -> Optional[float]:
        """The current promotion threshold (None while history warms up)."""
        with self._lock:
            return self._threshold_locked()

    def finish(self, trace_id: str, duration_s: float,
               error: bool = False, force: bool = False) -> bool:
        """Completion verdict for one request: promote its buffered spans
        into the active collector (threshold breach, error, or ``force``)
        or discard them. Always feeds the rolling window. Returns True
        iff promoted.

        ``force`` carries a promotion verdict made ELSEWHERE — on the
        front line the scorer process judges its half of a request's
        chain first and flags the response frame, and the worker forces
        its half so the cross-process chain promotes as a unit
        (docs/observability.md §"Tail sampling")."""
        with self._lock:
            spans = self._inflight.pop(trace_id, None)
            threshold = self._threshold_locked()
            self._durations.append(float(duration_s))
            # Strictly greater: a uniform-latency workload (everything ==
            # the p95) is the BORING case and must not promote 100%.
            promote = bool(error) or bool(force) or (
                threshold is not None and duration_s > threshold)
            if not promote:
                if spans is not None:
                    self.discarded += 1
                return False
            if spans is None:
                return False  # evicted before the verdict: already counted
            to_emit = [s for s in spans if not s.emitted]
            for s in to_emit:
                s.emitted = True
            if error:
                self.promoted_error += 1
            self.promoted += 1
        col = _ACTIVE
        if col is not None:
            for s in to_emit:
                col.add(s.event)
            col.instant("photon.trace.tail_promoted", "meta", {
                "trace_id": trace_id,
                "duration_ms": round(duration_s * 1e3, 3),
                "threshold_ms": (None if threshold is None
                                 else round(threshold * 1e3, 3)),
                "reason": "error" if error else "latency",
                "spans": len(to_emit),
            })
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "inflight": len(self._inflight),
                "capacity": self.capacity,
                "quantile": self.quantile,
                "window": len(self._durations),
                "threshold_s": self._threshold_locked(),
                "promoted": self.promoted,
                "promoted_error": self.promoted_error,
                "discarded": self.discarded,
                "evicted": self.evicted,
                "span_overflow": self.span_overflow,
            }


_ACTIVE: Optional[TraceCollector] = None
_TAIL: Optional[TailSampler] = None


def tail_sampler() -> Optional[TailSampler]:
    return _TAIL


def install_tail_sampler(sampler: Optional[TailSampler]) -> Optional[TailSampler]:
    """Install (or clear, with None) the process-wide tail sampler."""
    global _TAIL
    _TAIL = sampler
    return sampler


def uninstall_tail_sampler() -> Optional[TailSampler]:
    global _TAIL
    s = _TAIL
    _TAIL = None
    return s


def _env_tail_sampler() -> Optional[TailSampler]:
    """Build a TailSampler from the environment, or None when off.
    Malformed knob values degrade to defaults — a typo must never kill
    tracing (same contract as ``_env_sample``)."""
    raw = (os.environ.get("PHOTON_TRACE_TAIL") or "").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return None
    try:
        q = float(os.environ.get("PHOTON_TRACE_TAIL_QUANTILE", 0.95))
    except (TypeError, ValueError):
        q = 0.95
    if not 0.0 < q < 1.0:
        q = 0.95
    try:
        window = int(os.environ.get("PHOTON_TRACE_TAIL_WINDOW", 256))
    except (TypeError, ValueError):
        window = 256
    return TailSampler(quantile=q, window=window)


def tracing_active() -> bool:
    return _ACTIVE is not None


def active_collector() -> Optional[TraceCollector]:
    return _ACTIVE


def start_tracing(max_events: int = _DEFAULT_MAX_EVENTS) -> TraceCollector:
    """Install a process-wide collector (replacing any active one).
    ``PHOTON_TRACE_TAIL=1`` also installs a tail sampler, unless one is
    already installed (explicit installs win over the env default)."""
    global _ACTIVE, _TAIL
    _ACTIVE = TraceCollector(max_events=max_events)
    if _TAIL is None:
        _TAIL = _env_tail_sampler()
    return _ACTIVE


def stop_tracing(path: Optional[str] = None) -> Optional[TraceCollector]:
    """Uninstall the active collector; write it to ``path`` if given."""
    global _ACTIVE
    col = _ACTIVE
    _ACTIVE = None
    if col is not None and path:
        col.write(path)
    return col


class suspend_tracing:
    """``with suspend_tracing():`` — temporarily uninstall any active
    collector (restored on exit). Benchmarks use this so headline numbers
    are always measured tracing-off even under ``--trace-out``."""

    __slots__ = ("_prev",)

    def __enter__(self) -> None:
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = None

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev


class tracing:
    """``with tracing(path) as col:`` — scoped collector install, written on
    exit (restores whatever was active before, so traces can nest in
    tests)."""

    __slots__ = ("path", "max_events", "collector", "_prev")

    def __init__(self, path: Optional[str] = None,
                 max_events: int = _DEFAULT_MAX_EVENTS):
        self.path = path
        self.max_events = max_events
        self.collector: Optional[TraceCollector] = None

    def __enter__(self) -> TraceCollector:
        global _ACTIVE
        self._prev = _ACTIVE
        self.collector = TraceCollector(max_events=self.max_events)
        _ACTIVE = self.collector
        return self.collector

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev
        if self.path and self.collector is not None:
            self.collector.write(self.path)


class trace_span:
    """``with trace_span("descent.step", cat="descent", sweep=0) as sp:``

    Measures wall-clock into ``sp.seconds`` ALWAYS (so instrumented code can
    drop its hand-rolled ``perf_counter`` pairs); emits a complete event only
    when a collector is active. The span's ``trace_id`` defaults to the
    thread's current context (:func:`trace_context`); pass one explicitly at
    trace roots. ``sp.set(key=value)`` adds result attributes (iteration
    counts, row counts) before exit. An escaping exception is recorded as
    ``args["error"]``.
    """

    __slots__ = ("name", "cat", "args", "trace_id", "seconds", "_t0")

    def __init__(self, name: str, cat: str = "app",
                 trace_id: Optional[str] = None, **args):
        self.name = name
        self.cat = cat
        self.args = args
        self.trace_id = trace_id
        self.seconds = 0.0

    def set(self, **args) -> "trace_span":
        self.args.update(args)
        return self

    def __enter__(self) -> "trace_span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        col = _ACTIVE
        if col is None:
            return
        args = self.args
        tid = self.trace_id or current_trace_id()
        if tid is not None:
            args = {"trace_id": tid, **args}
        if exc_type is not None:
            args = {**args, "error": exc_type.__name__}
        col.complete(self.name, self.cat, self._t0, self.seconds,
                     {**args, "span_id": next(_span_ids)})


def instant(name: str, cat: str = "event", **args) -> None:
    """Emit an instant event (no duration) if tracing is active — fault
    firings, retrace warnings, admission rejections."""
    col = _ACTIVE
    if col is None:
        return
    tid = current_trace_id()
    if tid is not None:
        args = {"trace_id": tid, **args}
    col.instant(name, cat, args)
