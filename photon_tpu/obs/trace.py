"""Process-wide tracing: Chrome trace-event spans with propagated context.

Dapper-style (Sigelman et al., 2010) always-on, low-overhead tracing for the
whole stack — ingest, coordinate descent, optimizer solves, the serving
path — emitting the Chrome trace-event JSON format, so one run's timeline
opens directly in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.

Design constraints (docs/observability.md):

* **Near-zero cost when off.** Like ``faults.fault_point``, the hot-path
  check is one module-global read: :class:`trace_span` is a plain slotted
  class (no generator machinery) whose ``__exit__`` does nothing but two
  ``perf_counter`` reads when no collector is installed. Spans still
  measure wall-clock (``span.seconds``) so callers can keep using the
  measurement for records/logs whether or not tracing is on.
* **Propagated context.** Spans on a context-carrying thread inherit a
  ``trace_id``. A request's id is minted once at the edge
  (:func:`new_trace_id`) and attached via :func:`trace_context`; the
  serving micro-batcher stores the submitting request's id on the queue
  item, and the worker stamps it onto that row's queue-wait span and into
  the coalesced batch span's ``trace_ids`` list (a batch mixes several
  requests, so batch-level work — kernel, store resolve — correlates
  through that list rather than a single id).
* **One artifact.** Events buffer in memory (bounded) and
  :func:`stop_tracing` writes a single ``{"traceEvents": [...]}`` JSON
  object; ``scripts/obs_smoke.py`` validates the format in CI.

Span taxonomy (``cat`` → ``name``) is documented in docs/observability.md.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

__all__ = [
    "TraceCollector",
    "trace_span",
    "instant",
    "start_tracing",
    "stop_tracing",
    "suspend_tracing",
    "tracing_active",
    "tracing",
    "new_trace_id",
    "current_trace_id",
    "trace_context",
]

# Common clock for all collectors in this process: microsecond timestamps
# relative to module import, so events from collectors started at different
# times still order correctly within one process.
_EPOCH = time.perf_counter()

_span_ids = itertools.count(1)
_trace_ids = itertools.count(1)
_tls = threading.local()

# Default cap on buffered events: a leaked always-on collector must not grow
# host memory without bound. Dropped events are counted and reported in the
# written artifact ("photon.trace.dropped" metadata event).
_DEFAULT_MAX_EVENTS = 1_000_000


def new_trace_id() -> str:
    """Mint a fresh trace id (process-unique, human-scannable)."""
    return f"t{os.getpid():x}.{next(_trace_ids):x}"


def current_trace_id() -> Optional[str]:
    """The trace id attached to this thread, if any."""
    return getattr(_tls, "trace_id", None)


class trace_context:
    """``with trace_context(trace_id):`` — attach a trace id to this thread.

    Used at work-handoff boundaries: the producing thread records
    ``current_trace_id()`` next to the work item, the consuming thread
    re-enters it here so spans emitted while processing the item correlate
    with the originating request. Re-entrant; restores the previous id."""

    __slots__ = ("trace_id", "_prev")

    def __init__(self, trace_id: Optional[str]):
        self.trace_id = trace_id

    def __enter__(self) -> "trace_context":
        self._prev = getattr(_tls, "trace_id", None)
        _tls.trace_id = self.trace_id
        return self

    def __exit__(self, *exc) -> None:
        _tls.trace_id = self._prev


class TraceCollector:
    """Thread-safe in-memory buffer of Chrome trace events."""

    def __init__(self, max_events: int = _DEFAULT_MAX_EVENTS):
        self.max_events = int(max_events)
        self.events: list[dict] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._pid = os.getpid()

    def add(self, event: dict) -> None:
        with self._lock:
            if len(self.events) >= self.max_events:
                self.dropped += 1
                return
            self.events.append(event)

    def complete(
        self,
        name: str,
        cat: str,
        t0: float,
        dur_s: float,
        args: Optional[dict] = None,
    ) -> None:
        """One 'X' (complete) event; ``t0`` is a perf_counter value."""
        self.add({
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round((t0 - _EPOCH) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": args or {},
        })

    def instant(self, name: str, cat: str, args: Optional[dict] = None) -> None:
        """One 'i' (instant) event at now — fault firings, retrace warnings."""
        self.add({
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",
            "ts": round((time.perf_counter() - _EPOCH) * 1e6, 1),
            "pid": self._pid,
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": args or {},
        })

    def span_count(self, cat: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                1 for e in self.events
                if e["ph"] == "X" and (cat is None or e["cat"] == cat)
            )

    def to_dict(self) -> dict:
        with self._lock:
            events = list(self.events)
            dropped = self.dropped
        out = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            out["photon.trace.dropped"] = dropped
        return out

    def write(self, path: str) -> str:
        """Write the trace artifact as one JSON object (Perfetto-loadable)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f)
        return path


_ACTIVE: Optional[TraceCollector] = None


def tracing_active() -> bool:
    return _ACTIVE is not None


def active_collector() -> Optional[TraceCollector]:
    return _ACTIVE


def start_tracing(max_events: int = _DEFAULT_MAX_EVENTS) -> TraceCollector:
    """Install a process-wide collector (replacing any active one)."""
    global _ACTIVE
    _ACTIVE = TraceCollector(max_events=max_events)
    return _ACTIVE


def stop_tracing(path: Optional[str] = None) -> Optional[TraceCollector]:
    """Uninstall the active collector; write it to ``path`` if given."""
    global _ACTIVE
    col = _ACTIVE
    _ACTIVE = None
    if col is not None and path:
        col.write(path)
    return col


class suspend_tracing:
    """``with suspend_tracing():`` — temporarily uninstall any active
    collector (restored on exit). Benchmarks use this so headline numbers
    are always measured tracing-off even under ``--trace-out``."""

    __slots__ = ("_prev",)

    def __enter__(self) -> None:
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = None

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev


class tracing:
    """``with tracing(path) as col:`` — scoped collector install, written on
    exit (restores whatever was active before, so traces can nest in
    tests)."""

    __slots__ = ("path", "max_events", "collector", "_prev")

    def __init__(self, path: Optional[str] = None,
                 max_events: int = _DEFAULT_MAX_EVENTS):
        self.path = path
        self.max_events = max_events
        self.collector: Optional[TraceCollector] = None

    def __enter__(self) -> TraceCollector:
        global _ACTIVE
        self._prev = _ACTIVE
        self.collector = TraceCollector(max_events=self.max_events)
        _ACTIVE = self.collector
        return self.collector

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = self._prev
        if self.path and self.collector is not None:
            self.collector.write(self.path)


class trace_span:
    """``with trace_span("descent.step", cat="descent", sweep=0) as sp:``

    Measures wall-clock into ``sp.seconds`` ALWAYS (so instrumented code can
    drop its hand-rolled ``perf_counter`` pairs); emits a complete event only
    when a collector is active. The span's ``trace_id`` defaults to the
    thread's current context (:func:`trace_context`); pass one explicitly at
    trace roots. ``sp.set(key=value)`` adds result attributes (iteration
    counts, row counts) before exit. An escaping exception is recorded as
    ``args["error"]``.
    """

    __slots__ = ("name", "cat", "args", "trace_id", "seconds", "_t0")

    def __init__(self, name: str, cat: str = "app",
                 trace_id: Optional[str] = None, **args):
        self.name = name
        self.cat = cat
        self.args = args
        self.trace_id = trace_id
        self.seconds = 0.0

    def set(self, **args) -> "trace_span":
        self.args.update(args)
        return self

    def __enter__(self) -> "trace_span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        col = _ACTIVE
        if col is None:
            return
        args = self.args
        tid = self.trace_id or current_trace_id()
        if tid is not None:
            args = {"trace_id": tid, **args}
        if exc_type is not None:
            args = {**args, "error": exc_type.__name__}
        col.complete(self.name, self.cat, self._t0, self.seconds,
                     {**args, "span_id": next(_span_ids)})


def instant(name: str, cat: str = "event", **args) -> None:
    """Emit an instant event (no duration) if tracing is active — fault
    firings, retrace warnings, admission rejections."""
    col = _ACTIVE
    if col is None:
        return
    tid = current_trace_id()
    if tid is not None:
        args = {"trace_id": tid, **args}
    col.instant(name, cat, args)
