"""Unified observability layer (docs/observability.md).

One place the whole stack reports through, replacing the per-subsystem
patchwork (`Timed` log lines, the serving counter dict, hand-rolled
``perf_counter`` pairs in descent, the unlocked ``SCORE_KERNEL_STATS``
global):

* ``metrics``  — :class:`MetricsRegistry` of named counters/gauges/
  histograms with JSON snapshots and Prometheus text exposition
  (``GET /metrics?format=prom``);
* ``trace``    — :class:`trace_span`/:func:`instant` emitting Chrome
  trace-event JSON (Perfetto-loadable) with propagated trace ids, threaded
  through ingest, coordinate descent, optimizer solves, and the serving
  path (``--trace-out`` on every driver);
* ``retrace``  — jit-compilation sentinel: per-kernel trace counters and a
  loud warning (log + trace event) when a hot-path kernel retraces after
  warmup, plus device-memory watermark gauges.

Both hooks follow ``faults.fault_point``'s cost model: one module-global
read when inactive, so the instrumentation is always-on in production code.

The CONSUMERS of these artifacts live in ``photon_tpu.obs.analysis``
(imported on demand, not re-exported here): the trace-timeline analyzer
(``python -m photon_tpu.obs.analysis``), the backend-aware bench
regression gate (``scripts/bench_compare.py``), and the declarative SLO
watchdog (``obs.analysis.slo``) evaluated at serving flushes, supervisor
heartbeats, and bench end. ``photon_tpu.obs.live`` (same on-demand rule —
it imports the analysis layer) is the streaming fleet view behind
``python -m photon_tpu.cli.obs_driver``: the run-report detector folded
online over a live telemetry dir, served at ``GET /fleet``.
"""
from photon_tpu.obs.metrics import (
    Counter,
    Gauge,
    HistogramMetric,
    MetricsRegistry,
    REGISTRY,
    get_registry,
)
from photon_tpu.obs.trace import (
    ANCHOR_EVENT,
    TailSampler,
    TraceCollector,
    current_trace_id,
    install_tail_sampler,
    instant,
    new_trace_id,
    process_role,
    set_process_role,
    start_tracing,
    stop_tracing,
    suspend_tracing,
    tail_sampler,
    trace_context,
    trace_span,
    tracing,
    tracing_active,
    uninstall_tail_sampler,
)
from photon_tpu.obs import retrace

__all__ = [
    "ANCHOR_EVENT",
    "Counter",
    "Gauge",
    "HistogramMetric",
    "MetricsRegistry",
    "REGISTRY",
    "get_registry",
    "TailSampler",
    "TraceCollector",
    "current_trace_id",
    "install_tail_sampler",
    "instant",
    "new_trace_id",
    "process_role",
    "retrace",
    "set_process_role",
    "start_tracing",
    "stop_tracing",
    "suspend_tracing",
    "tail_sampler",
    "trace_context",
    "trace_span",
    "tracing",
    "tracing_active",
    "uninstall_tail_sampler",
]
