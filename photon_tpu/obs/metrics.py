"""Process-wide metrics registry: named counters/gauges/histograms.

One registry replaces the instrumentation patchwork that grew across PRs —
the serving server's hand-rolled counter dict, the batcher/cache/breaker
snapshot methods, and the unlocked ``SCORE_KERNEL_STATS`` module global.
Every instrument is thread-safe and resettable, and a registry exports two
views of the same state:

* :meth:`MetricsRegistry.snapshot` — the nested JSON dict the existing
  JSONL metrics pipeline (``utils.write_metrics_jsonl``) and ``/metrics``
  endpoint already speak;
* :meth:`MetricsRegistry.to_prometheus` — Prometheus text exposition
  (version 0.0.4), served at ``GET /metrics?format=prom`` so a standard
  Prometheus scrape covers latency, throughput, queue depth, and per-kernel
  retrace counts without a sidecar.

Label support is deliberately minimal (one flat ``dict`` of label pairs per
child); histograms reuse ``utils.LatencyHistogram`` and export as a
Prometheus *summary* (quantile series + ``_sum``/``_count``), which keeps
memory bounded under any traffic volume.

The module-level :data:`REGISTRY` is the process default (kernel retrace
counters, device-memory gauges); components that need isolation (one
``ScoringServer`` per test) construct their own registry and merge the
global view at exposition time.
"""
from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Mapping, Optional

from photon_tpu.utils.logging import LatencyHistogram

__all__ = [
    "Counter",
    "Gauge",
    "HistogramMetric",
    "MetricsRegistry",
    "REGISTRY",
    "get_registry",
]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{_prom_name(str(k))}="{_escape_label(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + body + "}"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if not f.is_integer() else str(int(f))


class Counter:
    """Monotonic counter, optionally with one level of labels.

    ``inc()`` bumps the unlabeled value; ``inc(kernel="score")`` bumps the
    ``{kernel="score"}`` child. ``value()``/``value(kernel=...)`` read.
    """

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._children: dict[tuple, float] = {}

    @staticmethod
    def _key(labels: Mapping[str, str]) -> tuple:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease by {n}")
        with self._lock:
            if labels:
                k = self._key(labels)
                self._children[k] = self._children.get(k, 0.0) + n
            else:
                self._value += n

    def value(self, **labels) -> float:
        with self._lock:
            if labels:
                return self._children.get(self._key(labels), 0.0)
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0
            self._children.clear()

    def collect(self) -> list[tuple[dict, float]]:
        """(labels, value) series, unlabeled first."""
        with self._lock:
            out = []
            if self._value or not self._children:
                out.append(({}, self._value))
            out.extend((dict(k), v) for k, v in sorted(self._children.items()))
            return out

    def snapshot_value(self):
        with self._lock:
            if self._children:
                return {
                    ".".join(v for _, v in k): val
                    for k, val in sorted(self._children.items())
                } | ({"": self._value} if self._value else {})
            return self._value

    def fold_series(self, labels: Mapping[str, str], value: float) -> None:
        """Merge primitive (obs/fleet.py): add one (labels, value) series
        from another process's shard. Counters SUM — bypasses ``inc``'s
        identifier-keyed kwargs so arbitrary label keys round-trip."""
        with self._lock:
            if labels:
                k = self._key(labels)
                self._children[k] = self._children.get(k, 0.0) + float(value)
            else:
                self._value += float(value)


class Gauge(Counter):
    """Settable instantaneous value; ``fn`` makes it a callback gauge read
    at collection time (queue depth, device-memory watermark)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help)
        self._fn = fn

    def set(self, v: float, **labels) -> None:
        with self._lock:
            if labels:
                self._children[self._key(labels)] = float(v)
            else:
                self._value = float(v)

    def inc(self, n: float = 1, **labels) -> None:  # gauges may move freely
        with self._lock:
            if labels:
                k = self._key(labels)
                self._children[k] = self._children.get(k, 0.0) + n
            else:
                self._value += n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def collect(self) -> list[tuple[dict, float]]:
        if self._fn is not None:
            try:
                v = self._fn()
            except Exception:  # noqa: BLE001 - a sick probe must not 500 /metrics
                return []
            if isinstance(v, Mapping):
                return [(dict(k) if isinstance(k, tuple) else {"key": str(k)},
                         float(val)) for k, val in sorted(v.items())]
            return [({}, float(v))] if v is not None else []
        return super().collect()

    def snapshot_value(self):
        if self._fn is not None:
            series = self.collect()
            if len(series) == 1 and not series[0][0]:
                return series[0][1]
            return {
                ".".join(f"{k}={v}" for k, v in sorted(lbl.items())): val
                for lbl, val in series
            }
        return super().snapshot_value()

    def fold_series(self, labels: Mapping[str, str], value: float) -> None:
        """Merge primitive: gauges are instantaneous, so a fold REPLACES
        the series value — latest-by-anchor ordering is the registry's job
        (``MetricsRegistry.merge`` folds shards in anchor order)."""
        with self._lock:
            if labels:
                self._children[self._key(labels)] = float(value)
            else:
                self._value = float(value)


class HistogramMetric:
    """A named ``LatencyHistogram`` exported as a Prometheus summary.

    Supports the same single flat label level as Counter/Gauge:
    ``observe(seconds, stage="kernel")`` lands the sample in a per-label
    child histogram (identical bin layout to the base, so children stay
    mergeable), and the exposition emits one quantile/sum/count series
    per child — p95 queue-wait vs p95 kernel is ONE scrape, not a
    trace-file autopsy (docs/serving.md §"Latency waterfall")."""

    kind = "summary"
    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help: str = "",
                 histogram: Optional[LatencyHistogram] = None):
        self.name = name
        self.help = help
        self.histogram = histogram or LatencyHistogram()
        self._children: dict[tuple, LatencyHistogram] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(labels: Mapping[str, str]) -> tuple:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def _blank_child(self) -> LatencyHistogram:
        """A zeroed histogram with EXACTLY the base's bin layout, so every
        child of one metric merges bin-for-bin across shards."""
        h = self.histogram
        return LatencyHistogram.from_state({
            "lo_ms": h._lo * 1e3,
            "bins_per_decade": h._bins_per_decade,
            "counts": [0] * len(h._counts),
            "sum": 0.0, "max": 0.0, "n": 0,
        })

    def child(self, **labels) -> LatencyHistogram:
        """The (created-on-first-use) child histogram for one label set;
        no labels returns the base histogram."""
        if not labels:
            return self.histogram
        k = self._key(labels)
        with self._lock:
            h = self._children.get(k)
            if h is None:
                h = self._blank_child()
                self._children[k] = h
            return h

    def observe(self, seconds: float, **labels) -> None:
        if labels:
            self.child(**labels).observe(seconds)
        else:
            self.histogram.observe(seconds)

    def reset(self) -> None:
        # LatencyHistogram has no public reset; replace it wholesale (racy
        # observers at worst land one sample in the discarded instance).
        self.histogram = LatencyHistogram()
        with self._lock:
            self._children.clear()

    def collect_children(self) -> list[tuple[dict, LatencyHistogram]]:
        with self._lock:
            return [(dict(k), h) for k, h in sorted(self._children.items())]

    def fold_child(self, labels: Mapping[str, str], state: Mapping) -> None:
        """Merge primitive (obs/fleet.py): fold one child's histogram
        state from another process's shard. Raises ValueError on a bin
        layout mismatch, same contract as ``LatencyHistogram.merge_state``."""
        k = self._key(labels)
        with self._lock:
            h = self._children.get(k)
            if h is None:
                self._children[k] = LatencyHistogram.from_state(state)
                return
        h.merge_state(state)

    def snapshot_value(self) -> dict:
        with self._lock:
            children = dict(self._children)
        if not children:
            return self.histogram.snapshot()
        out = {
            ".".join(v for _, v in k): h.snapshot()
            for k, h in sorted(children.items())
        }
        if self.histogram._n:
            out[""] = self.histogram.snapshot()
        return out

    def prometheus_lines(self, exposed_name: Optional[str] = None) -> list[str]:
        name = exposed_name or _prom_name(self.name)
        with self._lock:
            children = sorted(self._children.items())
        lines: list[str] = []

        def emit(h: LatencyHistogram, labels: dict) -> None:
            with h._lock:
                n, s = h._n, h._sum
            for q in self.QUANTILES:
                lines.append(
                    f"{name}{_prom_labels({**labels, 'quantile': str(q)})} "
                    f"{_prom_value(h.quantile_ms(q) / 1e3)}"
                )
            lines.append(f"{name}_sum{_prom_labels(labels)} {_prom_value(s)}")
            lines.append(
                f"{name}_count{_prom_labels(labels)} {_prom_value(n)}")

        if self.histogram._n or not children:
            emit(self.histogram, {})
        for k, h in children:
            emit(h, dict(k))
        return lines


class MetricsRegistry:
    """Name → instrument registry. Instruments are created on first use and
    shared thereafter (idempotent ``counter``/``gauge``/``histogram``
    accessors), so call sites don't coordinate setup order."""

    def __init__(self, prefix: str = "photon"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        # Fleet-merge bookkeeping (docs/observability.md §"Fleet view"):
        # per-shard retained states (shard_id -> (anchor, state)) so
        # re-merging a shard REPLACES its contribution instead of
        # double-counting, and per-gauge-series anchors so gauges resolve
        # latest-by-anchor whatever order shards arrive in.
        self._shard_states: dict[str, tuple] = {}
        self._fold_anchors: dict[tuple, float] = {}

    def _get(self, name: str, factory, kind) -> object:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help), Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        m = self._get(name, lambda: Gauge(name, help), Gauge)
        return m

    def gauge_fn(self, name: str, fn: Callable[[], float],
                 help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help, fn=fn), Gauge)

    def histogram(self, name: str, help: str = "",
                  histogram: Optional[LatencyHistogram] = None
                  ) -> HistogramMetric:
        return self._get(
            name, lambda: HistogramMetric(name, help, histogram),
            HistogramMetric,
        )

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Zero every instrument (tests; NOT for production use — counters
        are contractually monotonic between scrapes)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            reset = getattr(m, "reset", None)
            if reset is not None:
                reset()

    # ----------------------------------------------- fleet merge protocol
    #
    # The aggregation substrate the multi-process topology needs
    # (obs/fleet.py; docs/observability.md §"Fleet view"). Semantics:
    # counters SUM, gauges keep the value from the LATEST anchor (wall
    # clock at shard export), histograms merge bin counts exactly. The
    # pairwise fold is associative and commutative; idempotence ("a
    # double-collected shard changes nothing") comes from the shard
    # protocol — merge with a shard_id retains per-shard state and a
    # re-merge REPLACES that shard's contribution instead of adding it
    # again (the SolverCostTable.merge precedent from the mesh work).

    def dump_state(self) -> dict:
        """Full mergeable state: counter/gauge series with label dicts,
        histograms as raw bin counts (JSON-serializable — the registry-
        shard wire format)."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {}
        for name, m in sorted(metrics.items()):
            if isinstance(m, HistogramMetric):
                spec = {"kind": "summary", "help": m.help,
                        "state": m.histogram.state()}
                children = m.collect_children()
                if children:
                    spec["children"] = [[labels, h.state()]
                                        for labels, h in children]
                out[name] = spec
            else:
                out[name] = {
                    "kind": m.kind, "help": m.help,
                    "series": [[labels, value] for labels, value
                               in m.collect()],
                }
        return out

    def _fold(self, state: Mapping, anchor: float) -> None:
        import logging

        for name, spec in state.items():
            kind = spec.get("kind")
            help_ = spec.get("help", "")
            if kind == "summary":
                hstate = spec["state"]
                with self._lock:
                    absent = name not in self._metrics
                if absent:
                    # Create with the SHARD's bin layout, not the default:
                    # a component exporting a non-default LatencyHistogram
                    # must fold, not mismatch.
                    hm = self.histogram(
                        name, help_,
                        histogram=LatencyHistogram.from_state(hstate))
                else:
                    hm = self.histogram(name, help_)
                    try:
                        hm.histogram.merge_state(hstate)
                    except (ValueError, TypeError, KeyError) as e:
                        # One incompatible shard histogram must not kill the
                        # whole aggregation (the run report's contract) —
                        # skip the metric, loudly.
                        logging.getLogger("photon_tpu.obs").warning(
                            "fleet merge: skipping histogram %r (%s)",
                            name, e)
                        continue
                for labels, cstate in spec.get("children", ()):
                    try:
                        hm.fold_child(labels, cstate)
                    except (ValueError, TypeError, KeyError) as e:
                        logging.getLogger("photon_tpu.obs").warning(
                            "fleet merge: skipping histogram %r child %r "
                            "(%s)", name, labels, e)
            elif kind == "gauge":
                g = self.gauge(name, help_)
                for labels, value in spec.get("series", ()):
                    key = (name, tuple(sorted(
                        (str(k), str(v)) for k, v in labels.items())))
                    if anchor >= self._fold_anchors.get(key, float("-inf")):
                        self._fold_anchors[key] = anchor
                        g.fold_series(labels, value)
            elif kind == "counter":
                c = self.counter(name, help_)
                for labels, value in spec.get("series", ()):
                    if value:
                        c.fold_series(labels, value)
            # unknown kinds are skipped: a newer shard schema must not
            # kill an older aggregator

    @staticmethod
    def _hist_state_delta(ns: Mapping, os_: Mapping) -> Optional[dict]:
        """Elementwise ``new - old`` of one histogram state, or ``None``
        when the bin layout changed (caller folds the whole new state)."""
        if (len(ns.get("counts", ())) != len(os_.get("counts", ()))
                or ns.get("lo_ms") != os_.get("lo_ms")):
            return None
        return {
            **ns,
            "counts": [int(a) - int(b) for a, b
                       in zip(ns["counts"], os_["counts"])],
            "sum": float(ns["sum"]) - float(os_["sum"]),
            "n": int(ns["n"]) - int(os_["n"]),
            "max": max(float(ns["max"]), float(os_["max"])),
        }

    @staticmethod
    def _state_delta(new: Mapping, old: Mapping) -> dict:
        """``new - old`` as a foldable state: the replacement delta for a
        re-exported shard. Counters/histogram bins subtract elementwise
        (a restarted shard's lower counts fold as a negative correction);
        gauges pass through as-is (the fold's latest-anchor rule decides);
        a histogram max watermark is monotone (max of the two)."""
        out: dict = {}
        for name, spec in new.items():
            prev = old.get(name)
            if prev is None or prev.get("kind") != spec.get("kind"):
                out[name] = spec
                continue
            kind = spec.get("kind")
            if kind == "counter":
                old_by = {tuple(sorted((str(k), str(v))
                                       for k, v in labels.items())): value
                          for labels, value in prev.get("series", ())}
                series = []
                for labels, value in spec.get("series", ()):
                    key = tuple(sorted((str(k), str(v))
                                       for k, v in labels.items()))
                    series.append([labels, value - old_by.pop(key, 0.0)])
                for key, value in old_by.items():  # vanished series
                    series.append([dict(key), -value])
                out[name] = {**spec, "series": series}
            elif kind == "summary":
                diff = MetricsRegistry._hist_state_delta(
                    spec["state"], prev["state"])
                if diff is None:
                    out[name] = spec  # layout changed: fold whole (skipped
                    continue          # by merge_state's mismatch guard)
                delta_spec = {**spec, "state": diff}
                if "children" in spec or "children" in prev:
                    old_children = {
                        tuple(sorted((str(k), str(v))
                                     for k, v in labels.items())): st
                        for labels, st in prev.get("children", ())
                    }
                    children = []
                    for labels, st in spec.get("children", ()):
                        key = tuple(sorted((str(k), str(v))
                                           for k, v in labels.items()))
                        ost = old_children.pop(key, None)
                        cdiff = (None if ost is None
                                 else MetricsRegistry._hist_state_delta(
                                     st, ost))
                        children.append([labels, st if cdiff is None
                                         else cdiff])
                    # Vanished children (an in-place reset) fold as a
                    # negative correction, mirroring counter series.
                    for key, ost in old_children.items():
                        children.append([dict(key), {
                            **ost,
                            "counts": [-int(c) for c in ost["counts"]],
                            "sum": -float(ost["sum"]),
                            "n": -int(ost["n"]),
                            "max": float(ost["max"]),
                        }])
                    if children:
                        delta_spec["children"] = children
                    else:
                        delta_spec.pop("children", None)
                out[name] = delta_spec
            else:
                out[name] = spec
        return out

    def merge(self, other, anchor: Optional[float] = None,
              shard_id: Optional[str] = None) -> "MetricsRegistry":
        """Fold another registry (or a :meth:`dump_state` dict) into this
        one. ``anchor`` is the state's export wall time (defaults to now)
        — it decides which gauge value is "latest". With ``shard_id`` the
        merge is idempotent per shard: a re-merge with the same or an
        older anchor is a no-op; a newer anchor REPLACES that shard's
        previous contribution by folding the DELTA between the retained
        and new states — live instruments are updated in place, so the
        registry's own (non-shard) counters and any held instrument
        references stay attached and keep counting between merges."""
        state = other.dump_state() if isinstance(
            other, MetricsRegistry) else dict(other)
        anchor = time.time() if anchor is None else float(anchor)
        if shard_id is None:
            self._fold(state, anchor)
            return self
        prev = self._shard_states.get(shard_id)
        if prev is not None and prev[0] >= anchor:
            return self  # idempotent: double-collected shard changes nothing
        delta = state if prev is None else self._state_delta(state, prev[1])
        self._shard_states[shard_id] = (anchor, state)
        self._fold(delta, anchor)
        return self

    # ------------------------------------------------------------ exports

    def snapshot(self) -> dict:
        """Flat name → value dict (counters/gauges scalar or per-label dict,
        histograms their quantile snapshot)."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.snapshot_value() for name, m in sorted(metrics.items())}

    def to_prometheus(self, extra: Optional["MetricsRegistry"] = None) -> str:
        """Prometheus text exposition of this registry (merged with
        ``extra`` — typically the process-global registry — when given)."""
        with self._lock:
            metrics = dict(self._metrics)
        if extra is not None:
            with extra._lock:
                for name, m in extra._metrics.items():
                    metrics.setdefault(name, m)
        lines: list[str] = []
        for name in sorted(metrics):
            m = metrics[name]
            pname = _prom_name(f"{self.prefix}_{name}")
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if isinstance(m, HistogramMetric):
                lines.extend(m.prometheus_lines(pname))
            else:
                for labels, value in m.collect():
                    lines.append(
                        f"{pname}{_prom_labels(labels)} {_prom_value(value)}"
                    )
        return "\n".join(lines) + "\n"

    def now(self) -> float:  # patchable in tests
        return time.time()


# Process-global default registry: kernel retrace counters, device-memory
# gauges, ingest/descent counters — anything not owned by a single server.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
