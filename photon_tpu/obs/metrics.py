"""Process-wide metrics registry: named counters/gauges/histograms.

One registry replaces the instrumentation patchwork that grew across PRs —
the serving server's hand-rolled counter dict, the batcher/cache/breaker
snapshot methods, and the unlocked ``SCORE_KERNEL_STATS`` module global.
Every instrument is thread-safe and resettable, and a registry exports two
views of the same state:

* :meth:`MetricsRegistry.snapshot` — the nested JSON dict the existing
  JSONL metrics pipeline (``utils.write_metrics_jsonl``) and ``/metrics``
  endpoint already speak;
* :meth:`MetricsRegistry.to_prometheus` — Prometheus text exposition
  (version 0.0.4), served at ``GET /metrics?format=prom`` so a standard
  Prometheus scrape covers latency, throughput, queue depth, and per-kernel
  retrace counts without a sidecar.

Label support is deliberately minimal (one flat ``dict`` of label pairs per
child); histograms reuse ``utils.LatencyHistogram`` and export as a
Prometheus *summary* (quantile series + ``_sum``/``_count``), which keeps
memory bounded under any traffic volume.

The module-level :data:`REGISTRY` is the process default (kernel retrace
counters, device-memory gauges); components that need isolation (one
``ScoringServer`` per test) construct their own registry and merge the
global view at exposition time.
"""
from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Mapping, Optional

from photon_tpu.utils.logging import LatencyHistogram

__all__ = [
    "Counter",
    "Gauge",
    "HistogramMetric",
    "MetricsRegistry",
    "REGISTRY",
    "get_registry",
]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        f'{_prom_name(str(k))}="{_escape_label(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + body + "}"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if not f.is_integer() else str(int(f))


class Counter:
    """Monotonic counter, optionally with one level of labels.

    ``inc()`` bumps the unlabeled value; ``inc(kernel="score")`` bumps the
    ``{kernel="score"}`` child. ``value()``/``value(kernel=...)`` read.
    """

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0
        self._children: dict[tuple, float] = {}

    @staticmethod
    def _key(labels: Mapping[str, str]) -> tuple:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease by {n}")
        with self._lock:
            if labels:
                k = self._key(labels)
                self._children[k] = self._children.get(k, 0.0) + n
            else:
                self._value += n

    def value(self, **labels) -> float:
        with self._lock:
            if labels:
                return self._children.get(self._key(labels), 0.0)
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0
            self._children.clear()

    def collect(self) -> list[tuple[dict, float]]:
        """(labels, value) series, unlabeled first."""
        with self._lock:
            out = []
            if self._value or not self._children:
                out.append(({}, self._value))
            out.extend((dict(k), v) for k, v in sorted(self._children.items()))
            return out

    def snapshot_value(self):
        with self._lock:
            if self._children:
                return {
                    ".".join(v for _, v in k): val
                    for k, val in sorted(self._children.items())
                } | ({"": self._value} if self._value else {})
            return self._value


class Gauge(Counter):
    """Settable instantaneous value; ``fn`` makes it a callback gauge read
    at collection time (queue depth, device-memory watermark)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help)
        self._fn = fn

    def set(self, v: float, **labels) -> None:
        with self._lock:
            if labels:
                self._children[self._key(labels)] = float(v)
            else:
                self._value = float(v)

    def inc(self, n: float = 1, **labels) -> None:  # gauges may move freely
        with self._lock:
            if labels:
                k = self._key(labels)
                self._children[k] = self._children.get(k, 0.0) + n
            else:
                self._value += n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def collect(self) -> list[tuple[dict, float]]:
        if self._fn is not None:
            try:
                v = self._fn()
            except Exception:  # noqa: BLE001 - a sick probe must not 500 /metrics
                return []
            if isinstance(v, Mapping):
                return [(dict(k) if isinstance(k, tuple) else {"key": str(k)},
                         float(val)) for k, val in sorted(v.items())]
            return [({}, float(v))] if v is not None else []
        return super().collect()

    def snapshot_value(self):
        if self._fn is not None:
            series = self.collect()
            if len(series) == 1 and not series[0][0]:
                return series[0][1]
            return {
                ".".join(f"{k}={v}" for k, v in sorted(lbl.items())): val
                for lbl, val in series
            }
        return super().snapshot_value()


class HistogramMetric:
    """A named ``LatencyHistogram`` exported as a Prometheus summary."""

    kind = "summary"
    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, name: str, help: str = "",
                 histogram: Optional[LatencyHistogram] = None):
        self.name = name
        self.help = help
        self.histogram = histogram or LatencyHistogram()

    def observe(self, seconds: float) -> None:
        self.histogram.observe(seconds)

    def reset(self) -> None:
        # LatencyHistogram has no public reset; replace it wholesale (racy
        # observers at worst land one sample in the discarded instance).
        self.histogram = LatencyHistogram()

    def snapshot_value(self) -> dict:
        return self.histogram.snapshot()

    def prometheus_lines(self, exposed_name: Optional[str] = None) -> list[str]:
        h = self.histogram
        name = exposed_name or _prom_name(self.name)
        with h._lock:
            n, s = h._n, h._sum
        lines = []
        for q in self.QUANTILES:
            lines.append(
                f'{name}{{quantile="{q}"}} '
                f"{_prom_value(h.quantile_ms(q) / 1e3)}"
            )
        lines.append(f"{name}_sum {_prom_value(s)}")
        lines.append(f"{name}_count {_prom_value(n)}")
        return lines


class MetricsRegistry:
    """Name → instrument registry. Instruments are created on first use and
    shared thereafter (idempotent ``counter``/``gauge``/``histogram``
    accessors), so call sites don't coordinate setup order."""

    def __init__(self, prefix: str = "photon"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, factory, kind) -> object:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help), Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        m = self._get(name, lambda: Gauge(name, help), Gauge)
        return m

    def gauge_fn(self, name: str, fn: Callable[[], float],
                 help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help, fn=fn), Gauge)

    def histogram(self, name: str, help: str = "",
                  histogram: Optional[LatencyHistogram] = None
                  ) -> HistogramMetric:
        return self._get(
            name, lambda: HistogramMetric(name, help, histogram),
            HistogramMetric,
        )

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Zero every instrument (tests; NOT for production use — counters
        are contractually monotonic between scrapes)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            reset = getattr(m, "reset", None)
            if reset is not None:
                reset()

    # ------------------------------------------------------------ exports

    def snapshot(self) -> dict:
        """Flat name → value dict (counters/gauges scalar or per-label dict,
        histograms their quantile snapshot)."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.snapshot_value() for name, m in sorted(metrics.items())}

    def to_prometheus(self, extra: Optional["MetricsRegistry"] = None) -> str:
        """Prometheus text exposition of this registry (merged with
        ``extra`` — typically the process-global registry — when given)."""
        with self._lock:
            metrics = dict(self._metrics)
        if extra is not None:
            with extra._lock:
                for name, m in extra._metrics.items():
                    metrics.setdefault(name, m)
        lines: list[str] = []
        for name in sorted(metrics):
            m = metrics[name]
            pname = _prom_name(f"{self.prefix}_{name}")
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if isinstance(m, HistogramMetric):
                lines.extend(m.prometheus_lines(pname))
            else:
                for labels, value in m.collect():
                    lines.append(
                        f"{pname}{_prom_labels(labels)} {_prom_value(value)}"
                    )
        return "\n".join(lines) + "\n"

    def now(self) -> float:  # patchable in tests
        return time.time()


# Process-global default registry: kernel retrace counters, device-memory
# gauges, ingest/descent counters — anything not owned by a single server.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
