"""Bench-artifact loading + backend attribution, shared by the bench
regression gate (``scripts/bench_compare.py``), the doc-figure sync
(``scripts/sync_bench_docs.py``), and the timeline analyzer's roofline
join.

Two artifact shapes exist in the repo:

* ``BENCH_DETAILS*.json`` — the flat details dict ``bench.py`` flushes
  after every stage;
* ``BENCH_r*.json`` — the round driver's wrapper: ``{"n", "cmd", "rc",
  "tail", "parsed"}`` where ``parsed`` is the bench's final stdout line
  (``{"metric", "value", ..., "extra_metrics": <details>}``) when the
  driver managed to parse it, and ``tail`` keeps the last ~2K characters
  of stdout otherwise. The salvage path resynthesizes a partial details
  dict from the tail fragment (same trick as sync_bench_docs), so even a
  truncated round still compares on the metrics that survived.

Backend attribution is the comparability core (ROADMAP "bench trajectory
caveat": r3/r5 ran on CPU fallback while r2 hit the accelerator — their
ratios must never be diffed as a trend). Per metric, the backend resolves
in order: the metric's own nested ``backend`` stamp → ``stage_backends``
(stamped per stage since PR 4) → the artifact's top-level ``backend`` →
``provenance.backend_summary`` → ``"unknown"``. ``"unknown"`` never
compares equal to anything, including itself: a delta you cannot place on
one backend is not a delta.
"""
from __future__ import annotations

import dataclasses
import glob
import json
import os
from typing import Optional

__all__ = [
    "ArtifactError",
    "BenchArtifact",
    "load_bench_artifact",
    "load_bench_details",
    "newest_artifacts",
    "metric_backend",
    "normalize_backend",
    "flatten_metrics",
]


class ArtifactError(ValueError):
    """The file is not a readable bench artifact (schema error)."""


def load_bench_details(path: str) -> dict:
    """Details dict from either artifact shape; raises ArtifactError."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except OSError as e:
        raise ArtifactError(f"{path}: {e}") from e
    except ValueError as e:
        raise ArtifactError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(payload, dict):
        raise ArtifactError(f"{path}: top level is not a JSON object")
    if "tail" in payload and "cmd" in payload:  # BENCH_r* driver wrapper
        parsed = payload.get("parsed")
        if isinstance(parsed, dict):
            details = parsed.get("extra_metrics", parsed)
            if isinstance(details, dict):
                details = dict(details)
                # Surface the wrapper's headline as ordinary metrics so the
                # gate compares it like everything else.
                if isinstance(parsed.get("value"), (int, float)):
                    details.setdefault(
                        str(parsed.get("metric", "headline")),
                        parsed["value"])
                if isinstance(parsed.get("vs_baseline"), (int, float)):
                    details.setdefault("vs_baseline", parsed["vs_baseline"])
                return details
        return _salvage_tail(path, payload.get("tail") or "")
    return payload


def _salvage_tail(path: str, tail: str) -> dict:
    """Partial details from a truncated wrapper tail (last ~2K chars)."""
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                out = json.loads(line)
            except json.JSONDecodeError:
                continue
            got = out.get("extra_metrics", out)
            if isinstance(got, dict):
                return got
    # The wrapper keeps only the LAST ~2K chars, which usually cuts the
    # result line's head. Resynthesize an object from the first complete
    # top-level key in the fragment (it ends with the result line's two
    # closing braces: extra_metrics' and the outer object's).
    frag = tail.strip()
    cut = frag.find(', "')
    if cut >= 0 and frag.endswith("}}"):
        try:
            return json.loads("{" + frag[cut + 2:-1])
        except json.JSONDecodeError:
            pass
    raise ArtifactError(f"{path}: no JSON result line in tail")


@dataclasses.dataclass
class BenchArtifact:
    """One loaded artifact with its comparability context."""

    path: str
    details: dict

    @property
    def name(self) -> str:
        return os.path.basename(self.path)

    @property
    def round(self) -> Optional[int]:
        import re

        m = re.search(r"BENCH_r(\d+)", self.name)
        return int(m.group(1)) if m else None

    @property
    def provenance(self) -> dict:
        p = self.details.get("provenance")
        return p if isinstance(p, dict) else {}

    @property
    def written_at(self) -> Optional[str]:
        return self.details.get("written_at") or self.provenance.get(
            "written_at")

    def backend_for(self, metric: str) -> str:
        return metric_backend(self.details, metric)

    def metrics(self) -> dict:
        return flatten_metrics(self.details)


def load_bench_artifact(path: str) -> BenchArtifact:
    return BenchArtifact(path=path, details=load_bench_details(path))


def newest_artifacts(root: str, k: int = 2) -> list[str]:
    """The ``k`` newest PARSEABLE checked-in artifacts, returned
    oldest→newest (ready for compare). Smoke artifacts never participate;
    unparseable wrappers are skipped.

    Recency is judged from ARTIFACT CONTENT, not file mtime: a fresh git
    clone stamps every checked-in artifact with the checkout time, which
    would make "newest" (and the compare's oldest→newest orientation)
    arbitrary in CI. The key is (``written_at``, round number, name) —
    ``written_at`` is the measurement's own provenance; artifacts
    predating the stamp fall back to their round number; the basename
    breaks remaining ties deterministically."""
    cands = []
    for pat in ("BENCH_r*.json", "BENCH_DETAILS*.json"):
        for p in glob.glob(os.path.join(root, pat)):
            if "smoke" in os.path.basename(p):
                continue
            try:
                art = load_bench_artifact(p)
            except ArtifactError:
                continue
            cands.append((
                art.written_at or "",
                art.round if art.round is not None else -1,
                art.name,
                p,
            ))
    cands.sort()
    return [p for _, _, _, p in cands[-k:]]


# ----------------------------------------------------------- backend maps

_REAL_BACKENDS = ("tpu", "axon", "gpu")

# metric-name prefix -> bench stage name (stage_backends key). Order
# matters: first match wins, longest prefixes first.
_STAGE_PREFIXES = (
    ("game_scale_", "game_scale"),
    ("game_scoring", "game"),
    ("game_", "game"),
    ("serve_", "serve"),
    ("ingest_", "ingest"),
    ("owlqn_", "owlqn_tron"),
    ("tron_", "owlqn_tron"),
    ("tuner_", "tuner"),
    ("sparse_race", "sparse_race"),
    ("fixed_effect", "fixed_effect_lbfgs"),
    ("roofline", "roofline"),
    ("numpy_multicore_baseline", "numpy_baseline"),
)


def normalize_backend(raw) -> str:
    """Collapse stamp variants to one comparable token.

    ``cpu-fallback`` and the baseline's ``host-cpu (...)`` prose are all
    CPU measurements; anything unrecognized stays verbatim (two artifacts
    on the same exotic backend still compare)."""
    if not raw or not isinstance(raw, str):
        return "unknown"
    low = raw.strip().lower()
    if low.startswith("cpu") or low.startswith("host-cpu"):
        return "cpu"
    for b in _REAL_BACKENDS:
        if low == b or low.startswith(b + "-") or low.startswith(b + " "):
            return b
    return low.split()[0] if low else "unknown"


def _stage_of(metric: str) -> Optional[str]:
    if metric.startswith("stage_seconds."):
        return metric.split(".", 1)[1]
    for prefix, stage in _STAGE_PREFIXES:
        if metric.startswith(prefix):
            return stage
    return None


def metric_backend(details: dict, metric: str) -> str:
    """The backend one flattened metric was measured on (see module doc
    for the resolution order)."""
    # 1. the metric's own nested stamp (fixed_effect_lbfgs.backend,
    #    roofline.backend, numpy_multicore_baseline.backend)
    head = metric.split(".", 1)[0]
    nested = details.get(head)
    if isinstance(nested, dict) and isinstance(nested.get("backend"), str):
        return normalize_backend(nested["backend"])
    # 2. per-stage stamp (PR 4's stage_backends)
    stage = _stage_of(metric)
    backends = details.get("stage_backends")
    if stage and isinstance(backends, dict) and backends.get(stage):
        return normalize_backend(backends[stage])
    # 3. artifact-level stamp
    if isinstance(details.get("backend"), str):
        return normalize_backend(details["backend"])
    # 4. provenance backend summary (this PR's stamp)
    prov = details.get("provenance")
    if isinstance(prov, dict):
        summ = prov.get("backend_summary")
        if isinstance(summ, dict) and isinstance(summ.get("backend"), str):
            return normalize_backend(summ["backend"])
        if isinstance(summ, str):
            return normalize_backend(summ)
    return "unknown"


# Keys that are bookkeeping/provenance, never metrics to diff.
_SKIP_KEYS = frozenset({
    "written_at", "git_head", "backend", "backend_fallback_reason",
    "stage_backends", "skipped_stages", "stage_errors", "provenance",
    "completed", "smoke_mode", "tpu_recovery_attempts", "tpu_recovery_tail",
    "last_real_hardware", "resumed_from_written_at", "resumed_from_backend",
    "sparse_race_skipped", "sparse_race_done", "baseline_model",
    # The numpy baseline is the DENOMINATOR (host speed), not a bench
    # result — its run-to-run drift is why PR 4 pinned it; never scored.
    "numpy_multicore_baseline",
    "n", "cmd", "rc", "tail", "parsed", "slo",
})


def flatten_metrics(details: dict, prefix: str = "") -> dict:
    """Numeric leaves as dotted names: the comparable surface of an
    artifact. Bools, strings, lists, and bookkeeping keys are skipped."""
    out: dict[str, float] = {}
    for key, val in details.items():
        if not prefix and key in _SKIP_KEYS:
            continue
        name = f"{prefix}{key}"
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            out[name] = float(val)
        elif isinstance(val, dict):
            out.update(flatten_metrics(val, prefix=f"{name}."))
    return out
