"""Backend-aware bench regression gate (core; CLI in
``scripts/bench_compare.py``).

Compares two or more bench artifacts pairwise (oldest→newest in the given
order) and emits a machine-readable verdict. The one rule the repo's bench
history demands (ROADMAP "bench trajectory caveat"): **a delta is only a
delta on one backend.** r2 ran on the accelerator, r3/r5 on CPU fallback —
diffing them produces a 20× "regression" that is really a hardware swap.
So every metric resolves its measurement backend
(``artifacts.metric_backend``) and a pair whose backends differ — or
cannot be established on either side — is marked ``incomparable`` instead
of scored.

Per-metric verdicts:

* ``improved`` / ``regressed`` — same backend, relative change beyond the
  metric's noise threshold, signed by the metric's direction (throughput
  up = improved, latency up = regressed);
* ``unchanged``   — same backend, within the noise threshold;
* ``incomparable``— backends differ or unknown on either side;
* ``informational`` — no known better-direction (stage wall timings,
  request counts): delta reported, never scored;
* ``missing``     — present on one side only.

The pair verdict is ``regressed`` iff any comparable metric regressed;
the overall verdict aggregates pairs. The CLI is ADVISORY by default
(exit 0 regardless of verdicts, exit 2 on schema errors) so ci.sh can
print verdicts on every run without going red over a slow box; ``--strict``
turns regressions into exit 1 for release gates.
"""
from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

from photon_tpu.obs.analysis.artifacts import (
    BenchArtifact,
    load_bench_artifact,
)

__all__ = [
    "MetricDelta",
    "PairVerdict",
    "compare_artifacts",
    "compare_pair",
    "metric_direction",
    "DEFAULT_REL_THRESHOLD",
    "NOISE_THRESHOLDS",
]

# Default relative noise threshold: |delta| <= 10% is "unchanged".
DEFAULT_REL_THRESHOLD = 0.10

# Per-metric overrides where 10% is the wrong noise model: the roofline
# fraction is a ratio of two same-box measurements (tight), while tail
# latency and tiny stage timings jitter hard on shared hosts.
NOISE_THRESHOLDS = {
    "roofline.fraction_of_roofline": 0.05,
    "serve_p99_ms": 0.30,
    "serve_degraded_p99_ms": 0.30,
    "serve_p50_ms": 0.20,
    "serve_trace_overhead_p50_ms": 0.50,
    "vs_modeled_spark_cluster": 0.05,
    "vs_baseline_1core_raw": 0.05,
}

_HIGHER_BETTER_SUFFIXES = (
    "_per_sec", "_rows_per_sec", "_samples_per_sec", "_gbps",
    "_best_auc", "_mb_per_sec",
)
_HIGHER_BETTER_EXACT = (
    "roofline.fraction_of_roofline", "vs_baseline",
    "vs_modeled_spark_cluster", "vs_modeled_spark_cluster_live",
    "vs_baseline_1core_raw",
)
_LOWER_BETTER_SUFFIXES = ("_seconds", "_ms", "_p50_ms", "_p99_ms")
_LOWER_BETTER_EXACT = (
    "serve_shed", "serve_expired", "serve_breaker_opens",
)
# Stage wall timings and run-shape counts: honest numbers, no "better".
_INFORMATIONAL_PREFIXES = ("stage_seconds.", "tuner_trial")
_INFORMATIONAL_SUFFIXES = (
    "_requests", "_users", "_rows", "_n_users", "_trials", "_concurrency",
    "_host_cores", "_workers", "_nnz_per_row", "bytes_per_pass",
)


def metric_direction(name: str) -> Optional[str]:
    """'higher' | 'lower' | None (informational)."""
    if name.startswith(_INFORMATIONAL_PREFIXES) or name.endswith(
            _INFORMATIONAL_SUFFIXES):
        return None
    if name in _HIGHER_BETTER_EXACT or name.endswith(
            _HIGHER_BETTER_SUFFIXES):
        return "higher"
    if name in _LOWER_BETTER_EXACT or name.endswith(_LOWER_BETTER_SUFFIXES):
        return "lower"
    if name.endswith("_fraction"):
        return None  # direction depends on the fraction's meaning
    return None


@dataclasses.dataclass
class MetricDelta:
    metric: str
    old: Optional[float]
    new: Optional[float]
    backend_old: str
    backend_new: str
    verdict: str                 # improved|regressed|unchanged|incomparable|
    #                              informational|missing
    delta_pct: Optional[float] = None
    threshold_pct: Optional[float] = None
    direction: Optional[str] = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None or k in ("old", "new")}


@dataclasses.dataclass
class PairVerdict:
    old: str
    new: str
    verdict: str                 # ok|regressed|incomparable
    deltas: list
    notes: list

    def summary(self) -> dict:
        out: dict[str, int] = {}
        for d in self.deltas:
            out[d.verdict] = out.get(d.verdict, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "old": self.old,
            "new": self.new,
            "verdict": self.verdict,
            "summary": self.summary(),
            "notes": self.notes,
            "metrics": {d.metric: d.to_dict() for d in self.deltas},
        }


def _threshold_for(metric: str, overrides: Optional[Mapping]) -> float:
    if overrides and metric in overrides:
        return float(overrides[metric])
    return NOISE_THRESHOLDS.get(metric, DEFAULT_REL_THRESHOLD)


def compare_pair(
    old: BenchArtifact,
    new: BenchArtifact,
    thresholds: Optional[Mapping] = None,
) -> PairVerdict:
    om, nm = old.metrics(), new.metrics()
    deltas: list[MetricDelta] = []
    notes: list[str] = []

    po, pn = old.provenance, new.provenance
    # Backend-guard failover stamp (bench provenance.backend_guard): a run
    # that re-entered on CPU after a failed accelerator probe is a
    # DIFFERENT-hardware run by construction — the per-metric backend
    # resolution already refuses the deltas, but the note says WHY the
    # round is CPU, so the refusal reads as an incident, not a mystery.
    for prov, name, tag in ((po, old.name, "old"), (pn, new.name, "new")):
        fo = (prov.get("backend_guard") or {}).get("failover")
        if fo:
            notes.append(
                f"backend failover occurred in the {tag} artifact "
                f"({name}): [{fo.get('cause', 'unknown')}] → "
                f"{fo.get('to', 'cpu')} — this round ran on the failover "
                "backend; accelerator comparisons are withheld")
    # Cross-device-count refusal (same contract as the PR 6 cross-backend
    # refusal): an 8-device mesh round and a 1-device round measure
    # different programs (collectives, sharded kernels, per-shard feeds),
    # so every delta between them is a topology change, not a regression.
    ndo, ndn = po.get("n_devices"), pn.get("n_devices")
    devices_differ = bool(ndo and ndn and int(ndo) != int(ndn))
    if devices_differ:
        notes.append(
            f"device counts differ: {ndo} (old) vs {ndn} (new) — "
            "cross-device-count comparisons are incomparable; re-run on "
            "the same mesh for a scored verdict")
    for key, label in (("jax_version", "jax version"),
                       ("hostname", "host")):
        vo, vn = po.get(key), pn.get(key)
        if vo and vn and vo != vn:
            notes.append(
                f"{label} differs: {vo} (old) vs {vn} (new) — same-backend "
                f"deltas still reported, but treat absolute levels with "
                f"care")
    if not (po.get("hostname") and pn.get("hostname")):
        # Pre-provenance artifacts can't prove the two runs shared a box;
        # the ROADMAP trajectory caveat says cross-box absolutes mislead
        # (the r5→r6 box swap alone was ~11x on the fixed step), so every
        # verdict on such a pair ships with this warning attached.
        notes.append(
            "host provenance missing on "
            + ("both artifacts" if not (po.get("hostname")
                                        or pn.get("hostname"))
               else "one artifact")
            + " (predates the provenance stamp) — same-backend deltas may "
              "reflect a host swap, not a code change; prefer same-box "
              "A/Bs for absolute claims")

    for metric in sorted(set(om) | set(nm)):
        vo, vn = om.get(metric), nm.get(metric)
        bo = old.backend_for(metric) if metric in om else "unknown"
        bn = new.backend_for(metric) if metric in nm else "unknown"
        if vo is None or vn is None:
            deltas.append(MetricDelta(
                metric, vo, vn, bo, bn, "missing"))
            continue
        if (bo == "unknown" or bn == "unknown" or bo != bn
                or devices_differ):
            # A cross-backend (or unplaceable, or cross-device-count)
            # delta is not a regression and not an improvement — it is a
            # hardware/topology change.
            deltas.append(MetricDelta(metric, vo, vn, bo, bn, "incomparable"))
            continue
        # delta_pct is None when old == 0 (no relative change exists, and
        # float('inf') would make the --json verdict invalid JSON); the
        # change is then scored on the raw difference alone.
        pct = (vn - vo) / abs(vo) * 100.0 if vo != 0 else None
        direction = metric_direction(metric)
        thr = _threshold_for(metric, thresholds)
        if direction is None:
            deltas.append(MetricDelta(
                metric, vo, vn, bo, bn, "informational",
                delta_pct=round(pct, 2) if pct is not None else None))
            continue
        if vn == vo or (pct is not None and abs(pct) <= thr * 100.0):
            verdict = "unchanged"
        elif (vn > vo) == (direction == "higher"):
            verdict = "improved"
        else:
            verdict = "regressed"
        deltas.append(MetricDelta(
            metric, vo, vn, bo, bn, verdict,
            delta_pct=round(pct, 2) if pct is not None else None,
            threshold_pct=round(thr * 100.0, 1),
            direction=direction))

    scored = [d for d in deltas if d.verdict in
              ("improved", "regressed", "unchanged")]
    if any(d.verdict == "regressed" for d in scored):
        verdict = "regressed"
    elif scored:
        verdict = "ok"
    else:
        verdict = "incomparable"
        notes.append(
            "no metric pair shares an established backend — deltas "
            "withheld (see ROADMAP bench-trajectory caveat)")
    return PairVerdict(
        old=old.name, new=new.name, verdict=verdict, deltas=deltas,
        notes=notes)


def compare_artifacts(
    paths: Sequence[str],
    thresholds: Optional[Mapping] = None,
) -> dict:
    """Pairwise verdicts over ``paths`` in the given (oldest→newest)
    order; the machine-readable document ci.sh's advisory stage prints."""
    arts = [load_bench_artifact(p) for p in paths]
    pairs = [
        compare_pair(arts[i], arts[i + 1], thresholds=thresholds)
        for i in range(len(arts) - 1)
    ]
    overall = (
        "regressed" if any(p.verdict == "regressed" for p in pairs)
        else "ok" if any(p.verdict == "ok" for p in pairs)
        else "incomparable" if pairs else "nothing-to-compare"
    )
    return {
        "schema": "photon-bench-compare/1",
        "artifacts": [
            {"path": a.path, "round": a.round, "written_at": a.written_at}
            for a in arts
        ],
        "pairs": [p.to_dict() for p in pairs],
        "overall": overall,
    }


def format_verdict(doc: dict, top: int = 14) -> str:
    """Human-readable rendering of a compare_artifacts() document."""
    lines = []
    for pair in doc["pairs"]:
        lines.append(f"{pair['old']}  →  {pair['new']}:  "
                     f"{pair['verdict'].upper()}  {pair['summary']}")
        for note in pair["notes"]:
            lines.append(f"  note: {note}")
        shown = 0
        for name, d in pair["metrics"].items():
            if d["verdict"] in ("unchanged", "missing"):
                continue
            if shown >= top:
                lines.append("  ...")
                break
            shown += 1
            if d["verdict"] == "incomparable":
                lines.append(
                    f"  {name}: INCOMPARABLE "
                    f"({d['backend_old']} vs {d['backend_new']}) "
                    f"[{d['old']} vs {d['new']}]")
            else:
                arrow = {"improved": "+", "regressed": "!",
                         "informational": "."}[d["verdict"]]
                pct = d.get("delta_pct")
                lines.append(
                    f"  {arrow} {name}: {d['old']} → {d['new']} "
                    + (f"({pct:+.1f}%) " if pct is not None else "")
                    + d["verdict"])
    lines.append(f"overall: {doc['overall']}")
    return "\n".join(lines)
