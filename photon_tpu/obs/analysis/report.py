"""Unified fleet run report: one artifact for a multi-process run.

``python -m photon_tpu.obs.analysis report <run-dir>`` fuses everything a
run scattered across processes — per-process trace shards (merged onto
one wall-clock timeline via ``obs.fleet``), metrics-registry shards
(folded into one fleet registry), metrics JSONL histories, recovery /
patch journals, the newest bench artifact, and SLO results — into a
single JSON (schema :data:`REPORT_SCHEMA`) + human-readable markdown
report: topology table, per-process critical paths (``timeline.py``),
the restart/downshift/failover ledger, freshness watermarks, and a
**metrics-stream anomaly scan**.

Anomaly detector (the longitudinal complement to the pairwise bench
gate): for each watched series in the metrics JSONL history, a rolling
median/MAD robust z-score over a trailing window flags LEVEL SHIFTS —
``min_run`` consecutive points with ``|x - median| / (1.4826 * MAD)``
over the threshold. Median/MAD (not mean/stddev) so the detector's own
baseline shrugs off the spikes it is hunting; the consecutive-run
requirement keeps one-off warmup/GC spikes out of the anomaly count
(tuning knobs in docs/observability.md §"Fleet view").
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Iterable, Mapping, Optional, Sequence

REPORT_SCHEMA = "photon-fleet-report/1"

#: Series watched by default: the serving latency quantiles (lifetime
#: histograms — smooth on a healthy run, shifted by a real regression).
#: Throughput series are opt-in (--metric): interval rates legitimately
#: swing with offered load, which is variance, not anomaly.
DEFAULT_ANOMALY_METRICS = ("latency.p50_ms", "latency.p95_ms",
                           "latency.p99_ms")

DEFAULT_WINDOW = 16
DEFAULT_Z = 6.0
DEFAULT_MIN_HISTORY = 8
DEFAULT_MIN_RUN = 2

_MAD_SCALE = 1.4826  # MAD -> stddev under normality


# ------------------------------------------------------ anomaly detector


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_scores(
    values: Sequence[float],
    window: int = DEFAULT_WINDOW,
    min_history: int = DEFAULT_MIN_HISTORY,
) -> list:
    """Per-point robust z-scores against the TRAILING window (the point
    itself excluded — a level shift must not drag its own baseline).
    Points with fewer than ``min_history`` predecessors score None. A
    zero MAD (constant history) falls back to a 5%-of-median scale so
    constant-plus-epsilon series stay quiet instead of dividing by ~0."""
    out: list = []
    for i, x in enumerate(values):
        hist = values[max(0, i - window):i]
        if len(hist) < min_history:
            out.append(None)
            continue
        med = _median(hist)
        mad = _median([abs(h - med) for h in hist])
        scale = _MAD_SCALE * mad
        if scale <= 0:
            scale = max(abs(med) * 0.05, 1e-9)
        out.append(abs(x - med) / scale)
    return out


def detect_level_shifts(
    values: Sequence[float],
    window: int = DEFAULT_WINDOW,
    z_threshold: float = DEFAULT_Z,
    min_history: int = DEFAULT_MIN_HISTORY,
    min_run: int = DEFAULT_MIN_RUN,
) -> list[dict]:
    """Flag sustained level shifts in one series.

    A point is anomalous when its robust z-score crosses ``z_threshold``
    AND it belongs to a run of at least ``min_run`` consecutive
    over-threshold points (a lone spike is noise; a sustained shift is a
    regression). Returns one row per anomalous point:
    ``{"index", "value", "median", "z"}``.
    """
    vals = [float(v) for v in values]
    scores = robust_scores(vals, window=window, min_history=min_history)
    over = [s is not None and s >= z_threshold for s in scores]
    flagged: list[dict] = []
    i = 0
    while i < len(over):
        if not over[i]:
            i += 1
            continue
        j = i
        while j < len(over) and over[j]:
            j += 1
        if j - i >= max(1, int(min_run)):
            for k in range(i, j):
                hist = vals[max(0, k - window):k]
                flagged.append({
                    "index": k,
                    "value": round(vals[k], 6),
                    "median": round(_median(hist), 6),
                    "z": round(scores[k], 3),
                })
        i = j
    return flagged


def _iter_jsonl(path: str) -> Iterable[dict]:
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # torn tail from a live writer
                if isinstance(row, dict):
                    yield row
    except OSError:
        return


def _series_from_jsonl(path: str, metrics: Sequence[str]) -> dict:
    """Watched dotted-path series from one metrics JSONL history."""
    from photon_tpu.obs.analysis.artifacts import flatten_metrics

    series: dict[str, list] = {m: [] for m in metrics}
    for row in _iter_jsonl(path):
        flat = flatten_metrics(row)
        for m in metrics:
            v = flat.get(m)
            if v is not None:
                series[m].append(v)
    return {m: vals for m, vals in series.items() if vals}


def anomaly_scan(
    jsonl_paths: Sequence[str],
    metrics: Optional[Sequence[str]] = None,
    window: int = DEFAULT_WINDOW,
    z_threshold: float = DEFAULT_Z,
    min_run: int = DEFAULT_MIN_RUN,
) -> dict:
    """Run the level-shift detector over every watched series in every
    metrics JSONL file. Returns ``{"series": [...], "n_anomalies": N}``
    — one series row per (file, metric) with its point count and flagged
    anomalies."""
    metrics = tuple(metrics or DEFAULT_ANOMALY_METRICS)
    rows = []
    total = 0
    for path in jsonl_paths:
        for name, values in sorted(_series_from_jsonl(path,
                                                      metrics).items()):
            flags = detect_level_shifts(values, window=window,
                                        z_threshold=z_threshold,
                                        min_run=min_run)
            total += len(flags)
            rows.append({
                "file": os.path.abspath(path),
                "metric": name,
                "points": len(values),
                "anomalies": flags,
            })
    return {
        "metrics_watched": list(metrics),
        "window": window,
        "z_threshold": z_threshold,
        "min_run": min_run,
        "series": rows,
        "n_anomalies": total,
    }


# ----------------------------------------------------------- run report


def _ledger_counts(rows: Sequence[Mapping]) -> dict:
    """Event/cause counts over the merged journal stream — the
    restart/downshift/failover ledger header."""
    by_event: dict[str, int] = {}
    by_cause: dict[str, int] = {}
    for r in rows:
        ev = str(r.get("event", "?"))
        by_event[ev] = by_event.get(ev, 0) + 1
        cause = r.get("cause")
        if cause:
            by_cause[str(cause)] = by_cause.get(str(cause), 0) + 1
    return {"rows": len(rows), "by_event": by_event, "by_cause": by_cause}


def _control_section(rows: Sequence[Mapping]) -> Optional[dict]:
    """Digest of the control plane's decision ledger
    (``control-ledger.jsonl`` — docs/control.md §ledger): action/outcome
    tallies, canary verdicts, and the suppression counts that evidence
    the damping guarantees (a loop that never records a cooldown or
    budget suppression was never tested against pressure)."""
    if not rows:
        return None
    actions: dict[str, int] = {}
    outcomes = {"ok": 0, "failed": 0}
    suppressed: dict[str, int] = {}
    canary = {"promoted": 0, "rolled_back": 0, "last_verdict": None}
    for r in rows:
        ev = str(r.get("event", "?"))
        if ev == "action":
            a = str(r.get("action", "?"))
            actions[a] = actions.get(a, 0) + 1
        elif ev == "action_outcome":
            outcomes["ok" if r.get("ok") else "failed"] += 1
        elif ev == "action_suppressed":
            reason = str(r.get("reason", "?"))
            suppressed[reason] = suppressed.get(reason, 0) + 1
        elif ev == "canary_promote":
            canary["promoted"] += 1
            canary["last_verdict"] = "promote"
        elif ev == "canary_rollback":
            canary["rolled_back"] += 1
            canary["last_verdict"] = "rollback"
    return {
        **_ledger_counts(rows),
        "actions": actions,
        "outcomes": outcomes,
        "suppressed": suppressed,
        "canary": canary,
        "events": list(rows)[-200:],
    }


def _freshness_watermarks(metrics_jsonl: Sequence[str]) -> dict:
    """Latest non-empty ``freshness`` block per metrics history file."""
    out = {}
    for path in metrics_jsonl:
        last = None
        for row in _iter_jsonl(path):
            fr = row.get("freshness")
            if isinstance(fr, dict) and fr:
                last = fr
        if last is not None:
            out[os.path.abspath(path)] = last
    return out


def _last_slo(metrics_jsonl: Sequence[str]) -> Optional[dict]:
    last = None
    for path in metrics_jsonl:
        for row in _iter_jsonl(path):
            slo = row.get("slo")
            if isinstance(slo, dict):
                last = {"file": os.path.abspath(path), **slo}
    return last


def _replication_section(snapshot: Mapping) -> Optional[dict]:
    """Replication posture from the folded fleet metrics snapshot
    (docs/serving.md §"Replication"): per-replica delta-log counters and
    watermarks (series labeled ``replica=<id>`` fold to ``{id: value}``)
    plus router traffic totals. ``None`` when the run had no replicated
    tier — the section renders only where it means something."""

    def series(name: str) -> dict:
        # Only labeled series name a replica; a scalar here is a
        # never-incremented counter's unlabeled zero, not a replica.
        v = snapshot.get(name)
        if isinstance(v, dict):
            return {k: val for k, val in v.items() if k}
        return {}

    replicas: dict[str, dict] = {}
    for field, metric in (
        ("applied", "replica_deltas_applied_total"),
        ("replayed", "replica_deltas_replayed_total"),
        ("duplicates_skipped", "replica_duplicate_seqs_total"),
        ("catchups", "replica_catchups_total"),
        ("apply_errors", "replica_apply_errors_total"),
        ("seq_watermark", "replica_seq_watermark"),
        ("lag", "replica_lag"),
    ):
        for rid, val in series(metric).items():
            replicas.setdefault(rid, {})[field] = val
    router = {}
    for field, metric in (
        ("requests", "router_requests_total"),
        ("upstream_requests", "router_upstream_requests_total"),
        ("retries", "router_retries_total"),
        ("upstream_errors", "router_upstream_errors_total"),
        ("healthy_replicas", "router_healthy_replicas"),
        ("known_replicas", "router_known_replicas"),
    ):
        v = snapshot.get(metric)
        if v is not None:
            router[field] = v
    if not replicas and not router:
        return None
    marks = sorted({v.get("seq_watermark") for v in replicas.values()
                    if v.get("seq_watermark") is not None})
    return {
        "replicas": replicas,
        "router": router,
        # Same watermark on every replica = the fleet converged; a spread
        # names exactly which replica is behind.
        "converged": len(marks) <= 1,
        "seq_watermarks": marks,
    }


def _mesh_section(snapshot: Mapping,
                  ledger: Sequence[Mapping]) -> Optional[dict]:
    """Elastic multi-host mesh posture (docs/scaling.md §"Multi-host
    mesh"): the newest membership epoch and shard assignment from the
    merged ``mesh-epochs`` ledger, the host-loss / rejoin history, and
    per-host beacon liveness from the folded
    ``host_beacon_age_seconds{host=...}`` gauges — a dead host shows up
    here as a frozen, climbing age WITHOUT anyone reading beacon files.
    ``None`` when the run had no mesh."""
    beacons = snapshot.get("host_beacon_age_seconds")
    beacons = ({k: v for k, v in beacons.items() if k}
               if isinstance(beacons, dict) else {})
    epochs = [r for r in ledger
              if r.get("event") in ("mesh_formed", "mesh_shrunk",
                                    "mesh_grown")]
    if not beacons and not epochs:
        return None
    newest = max(epochs, default=None,
                 key=lambda r: (int(r.get("epoch", -1)), r.get("t", 0.0)))
    losses = [{"host": r.get("host"), "epoch": r.get("epoch"),
               "time": r.get("time"),
               "beacon_age_seconds": r.get("beacon_age_seconds")}
              for r in ledger if r.get("event") == "host_lost"]
    rejoins = [{"host": r.get("host"), "epoch": r.get("epoch"),
                "time": r.get("time")}
               for r in ledger if r.get("event") == "host_rejoined"]
    redist = [r for r in ledger
              if r.get("event") == "shard_redistributed"]
    return {
        "epoch": None if newest is None else int(newest.get("epoch", -1)),
        "members": None if newest is None else newest.get("members"),
        "files": None if newest is None else newest.get("files"),
        "epoch_rows": len(epochs),
        "host_losses": losses,
        "rejoins": rejoins,
        "redistributions": len(redist),
        "beacon_age_seconds": beacons,
    }


def _newest_bench(paths: Sequence[str]) -> Optional[dict]:
    """Summarize the newest parseable bench artifact found in the run
    dir (recency from artifact content, per artifacts.newest_artifacts'
    contract — mtime lies after a fresh clone)."""
    from photon_tpu.obs.analysis.artifacts import (
        ArtifactError,
        load_bench_artifact,
    )

    best = None
    for p in paths:
        try:
            art = load_bench_artifact(p)
        except ArtifactError:
            continue
        key = (art.details.get("written_at") or "", art.name)
        if best is None or key > best[0]:
            best = (key, art)
    if best is None:
        return None
    art = best[1]
    prov = art.details.get("provenance") or {}
    return {
        "artifact": os.path.abspath(art.path),
        "written_at": art.details.get("written_at"),
        "backend": (prov.get("backend_summary") or {}).get("backend"),
        "metrics": art.details.get("metrics") or {},
    }


def build_report(
    run_dir: str,
    metrics: Optional[Sequence[str]] = None,
    window: int = DEFAULT_WINDOW,
    z_threshold: float = DEFAULT_Z,
    min_run: int = DEFAULT_MIN_RUN,
    merged_trace_out: Optional[str] = None,
    top: int = 5,
) -> dict:
    """Fuse one run directory's telemetry into the fleet report dict."""
    from photon_tpu.obs import fleet
    from photon_tpu.obs.analysis.timeline import (
        TraceParseError,
        analyze_trace,
    )

    files = fleet.discover(run_dir)
    warnings: list[str] = []

    # -- per-process timelines + merged trace -----------------------------
    topology = []
    per_process = {}
    mergeable = []
    for path in files.traces:
        try:
            _, anchor = fleet.load_trace_shard(path)
            mergeable.append(path)
        except fleet.FleetMergeError as e:
            if e.merged_doc:
                # A prior report's --merged-trace output living in the
                # run dir: not a shard, not a process — skip it entirely
                # (re-ingesting it would double-count every span).
                continue
            warnings.append(str(e))
            anchor = None
        try:
            rep = analyze_trace(path)
        except TraceParseError as e:
            warnings.append(f"{path}: {e}")
            continue
        role = (anchor or {}).get("role", "unknown")
        pid = (anchor or {}).get("pid")
        key = f"{role}.{pid}" if pid is not None else os.path.basename(path)
        topology.append({
            "role": role,
            "pid": pid,
            "hostname": (anchor or {}).get("hostname"),
            "trace": os.path.abspath(path),
            "anchored": anchor is not None,
            "wall_time": (anchor or {}).get("wall_time"),
            "spans": rep.n_spans,
            "wall_seconds": round(rep.wall_seconds, 6),
        })
        per_process[key] = {
            "trace": os.path.abspath(path),
            "wall_seconds": round(rep.wall_seconds, 6),
            "critical_path": rep.critical_path(top=top),
            "bottleneck": rep.bottleneck(),
            "queue_wait": rep.queue_wait,
            "unclosed_spans": rep.unclosed_spans,
        }
    merged_trace: Optional[dict] = None
    if mergeable:
        doc = fleet.merge_traces(mergeable, out_path=merged_trace_out)
        joins = fleet.cross_process_joins(doc)
        from photon_tpu.obs.analysis.timeline import analyze_events

        mrep = analyze_events(doc["traceEvents"])
        merged_trace = {
            "path": (os.path.abspath(merged_trace_out)
                     if merged_trace_out else None),
            "shards": doc["photon.fleet"]["shards"],
            "origin_wall_time": doc["photon.fleet"]["origin_wall_time"],
            "spans": mrep.n_spans,
            "wall_seconds": round(mrep.wall_seconds, 6),
            "roles": sorted({s["role"]
                             for s in doc["photon.fleet"]["shards"]}),
            "cross_process_joins": joins[:50],
            "n_cross_process_joins": len(joins),
        }

    # -- fleet metrics -----------------------------------------------------
    agg, shard_meta = fleet.collect_shards(files.registry_shards)
    metrics_snapshot = agg.snapshot()

    # -- merged recovery ledger -------------------------------------------
    ledger = fleet.merge_journals(files.journals)
    patch_rows = fleet.merge_journals(files.patch_journals)
    control_rows = fleet.merge_journals(files.control_ledgers)

    report = {
        "schema": REPORT_SCHEMA,
        "run_dir": os.path.abspath(run_dir),
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "topology": sorted(topology,
                           key=lambda t: (t["role"], t["pid"] or 0)),
        "merged_trace": merged_trace,
        "per_process": per_process,
        "metrics": {
            "shards": shard_meta,
            "snapshot": metrics_snapshot,
        },
        "replication": _replication_section(metrics_snapshot),
        "mesh": _mesh_section(metrics_snapshot, ledger),
        "recovery_ledger": {
            **_ledger_counts(ledger),
            "events": ledger[-200:],
        },
        "patch_ledger": {"rows": len(patch_rows)},
        "control": _control_section(control_rows),
        "freshness": _freshness_watermarks(files.metrics_jsonl),
        "slo": _last_slo(files.metrics_jsonl),
        "bench": _newest_bench(files.bench_artifacts),
        "anomalies": anomaly_scan(files.metrics_jsonl, metrics=metrics,
                                  window=window, z_threshold=z_threshold,
                                  min_run=min_run),
        "warnings": warnings,
    }
    return report


def format_markdown(report: Mapping, top: int = 5) -> str:
    """Human-readable render of :func:`build_report`'s dict."""
    lines = [f"# Fleet run report — {report['run_dir']}",
             f"generated {report['generated_at']}  ·  schema "
             f"`{report['schema']}`", ""]

    lines.append("## Topology")
    topo = report.get("topology") or []
    if topo:
        lines += ["", "| role | pid | host | spans | wall (s) | anchored |",
                  "|---|---|---|---|---|---|"]
        for t in topo:
            lines.append(
                f"| {t['role']} | {t['pid']} | {t.get('hostname')} | "
                f"{t['spans']} | {t['wall_seconds']} | "
                f"{'yes' if t['anchored'] else 'NO (unmergeable)'} |")
    else:
        lines.append("_no trace shards found_")

    mt = report.get("merged_trace")
    lines += ["", "## Merged timeline"]
    if mt:
        lines.append(
            f"{mt['spans']} spans over {mt['wall_seconds']}s across roles "
            f"{', '.join(mt['roles'])}; {mt['n_cross_process_joins']} "
            "cross-process trace-id join(s).")
        for j in mt["cross_process_joins"][:top]:
            lines.append(
                f"- `{j['trace_id']}` spans {len(j['pids'])} processes "
                f"({', '.join(j['roles'])}; {j['events']} events)")
    else:
        lines.append("_no mergeable (anchored) trace shards_")

    lines += ["", "## Per-process critical paths"]
    for key, pp in sorted((report.get("per_process") or {}).items()):
        bn = pp.get("bottleneck")
        lines.append(f"### {key} — "
                     + (f"bottleneck `{bn['cat']}:{bn['name']}` "
                        f"({bn['share']:.0%})" if bn else "empty"))
        for row in (pp.get("critical_path") or [])[:top]:
            lines.append(f"- {row['share'] * 100:5.1f}%  "
                         f"{row['cat']}:{row['name']} "
                         f"({row['owned_s'] * 1e3:.2f} ms)")

    led = report.get("recovery_ledger") or {}
    lines += ["", "## Restart / downshift / failover ledger",
              f"{led.get('rows', 0)} journal row(s)."]
    for ev, n in sorted((led.get("by_event") or {}).items()):
        lines.append(f"- {ev}: {n}")
    if led.get("by_cause"):
        lines.append("by classified cause: "
                     + ", ".join(f"{c}={n}" for c, n
                                 in sorted(led["by_cause"].items())))

    mesh = report.get("mesh")
    if mesh:
        lines += ["", "## Mesh"]
        if mesh.get("members") is not None:
            files = mesh.get("files") or {}
            lines += [f"epoch {mesh.get('epoch')} — members "
                      f"{mesh.get('members')} "
                      f"({mesh.get('epoch_rows')} epoch row(s), "
                      f"{mesh.get('redistributions')} redistribution(s))",
                      "", "| host | file shard | beacon age (s) |",
                      "|---|---|---|"]
            beacons = mesh.get("beacon_age_seconds") or {}
            for h in mesh["members"]:
                age = beacons.get(str(h))
                lines.append(
                    f"| {h} | {', '.join(files.get(str(h), []) or files.get(h, []))} | "
                    + (f"{age:.2f}" if isinstance(age, (int, float))
                       else "?") + " |")
        for row in mesh.get("host_losses") or []:
            age = row.get("beacon_age_seconds")
            lines.append(
                f"- host LOST: {row['host']} at epoch {row['epoch']} "
                f"({row.get('time')}"
                + (f", beacon age {age:.2f}s" if isinstance(age, (int, float))
                   else "") + ")")
        for row in mesh.get("rejoins") or []:
            lines.append(f"- host rejoined: {row['host']} at epoch "
                         f"{row['epoch']} ({row.get('time')})")

    rep = report.get("replication")
    if rep:
        lines += ["", "## Replication"]
        reps = rep.get("replicas") or {}
        if reps:
            lines += ["| replica | watermark | lag | applied | dups "
                      "skipped | catch-ups | apply errors |",
                      "|---|---|---|---|---|---|---|"]
            for rid, row in sorted(reps.items()):
                lines.append(
                    f"| {rid} | {row.get('seq_watermark')} | "
                    f"{row.get('lag')} | {row.get('applied')} | "
                    f"{row.get('duplicates_skipped', 0)} | "
                    f"{row.get('catchups', 0)} | "
                    f"{row.get('apply_errors', 0)} |")
            lines.append("converged" if rep.get("converged")
                         else "**NOT CONVERGED**: watermarks "
                              f"{rep.get('seq_watermarks')}")
        rt = rep.get("router") or {}
        if rt:
            lines.append(
                "router: " + ", ".join(
                    f"{k}={json.dumps(v)}" for k, v in sorted(rt.items())))

    ctl = report.get("control")
    if ctl:
        out = ctl.get("outcomes") or {}
        lines += ["", "## Control",
                  f"{ctl.get('rows', 0)} ledger row(s); actions ok="
                  f"{out.get('ok', 0)}, failed={out.get('failed', 0)}."]
        for ev, n in sorted((ctl.get("by_event") or {}).items()):
            lines.append(f"- {ev}: {n}")
        if ctl.get("actions"):
            lines.append("actions by lever: "
                         + ", ".join(f"{a}={n}" for a, n
                                     in sorted(ctl["actions"].items())))
        if ctl.get("suppressed"):
            lines.append("suppressed (damping): "
                         + ", ".join(f"{r}={n}" for r, n
                                     in sorted(ctl["suppressed"].items())))
        can = ctl.get("canary") or {}
        if can.get("promoted") or can.get("rolled_back"):
            lines.append(
                f"canary: promoted={can.get('promoted', 0)}, "
                f"rolled_back={can.get('rolled_back', 0)}, "
                f"last verdict={can.get('last_verdict')}")

    fresh = report.get("freshness") or {}
    lines += ["", "## Freshness watermarks"]
    if fresh:
        for path, fr in sorted(fresh.items()):
            lines.append(f"- `{os.path.basename(path)}`: "
                         + ", ".join(f"{k}={v}" for k, v
                                     in sorted(fr.items())))
    else:
        lines.append("_none recorded_")

    an = report.get("anomalies") or {}
    lines += ["", "## Metrics-stream anomalies",
              f"{an.get('n_anomalies', 0)} anomalous point(s) across "
              f"{len(an.get('series') or [])} watched series "
              f"(window={an.get('window')}, z>={an.get('z_threshold')}, "
              f"min_run={an.get('min_run')})."]
    for s in an.get("series") or []:
        if s["anomalies"]:
            first = s["anomalies"][0]
            lines.append(
                f"- **{s['metric']}** in `{os.path.basename(s['file'])}`: "
                f"{len(s['anomalies'])} point(s), first at index "
                f"{first['index']} (value {first['value']} vs median "
                f"{first['median']}, z={first['z']})")

    if report.get("slo"):
        lines += ["", "## SLO (last judged)",
                  f"`{json.dumps(report['slo'])[:500]}`"]
    if report.get("bench"):
        b = report["bench"]
        lines += ["", "## Newest bench artifact",
                  f"`{os.path.basename(b['artifact'])}` "
                  f"(written {b.get('written_at')}, backend "
                  f"{b.get('backend')}; {len(b.get('metrics') or {})} flat "
                  "metrics)"]
    if report.get("warnings"):
        lines += ["", "## Warnings"]
        lines += [f"- {w}" for w in report["warnings"][:20]]
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------------- CLI


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m photon_tpu.obs.analysis report",
        description="Fuse a multi-process run's telemetry (trace shards, "
                    "registry shards, metrics JSONL, recovery journals, "
                    "bench artifacts) into one fleet report.",
    )
    ap.add_argument("run_dir", help="run/telemetry directory "
                                    "(--telemetry-dir convention)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the full report JSON here ('-' for stdout)")
    ap.add_argument("--md", dest="md_out", default=None,
                    help="write the markdown render here")
    ap.add_argument("--merged-trace", default=None,
                    help="also write the merged Perfetto-loadable "
                         "timeline here")
    ap.add_argument("--metric", action="append", default=None,
                    help="watched anomaly series (dotted path into the "
                         "metrics JSONL rows; repeatable; default: "
                         + ", ".join(DEFAULT_ANOMALY_METRICS) + ")")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing window for the rolling median/MAD")
    ap.add_argument("--z-threshold", type=float, default=DEFAULT_Z,
                    help="robust z-score a point must cross")
    ap.add_argument("--min-run", type=int, default=DEFAULT_MIN_RUN,
                    help="consecutive over-threshold points required "
                         "(>=2 suppresses lone spikes)")
    ap.add_argument("--top", type=int, default=5,
                    help="rows per critical-path table in the markdown")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.run_dir):
        print(f"report: {args.run_dir}: not a directory", file=sys.stderr)
        return 2
    report = build_report(
        args.run_dir, metrics=args.metric, window=args.window,
        z_threshold=args.z_threshold, min_run=args.min_run,
        merged_trace_out=args.merged_trace, top=args.top,
    )
    # File artifacts FIRST: `report ... --json out.json | head` must still
    # produce out.json — a consumer closing stdout early (BrokenPipeError
    # on the markdown print below) must never cost the JSON artifact.
    if args.json_out and args.json_out != "-":
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2)
        print(f"report JSON written to {args.json_out}", file=sys.stderr)
    if args.md_out:
        with open(args.md_out, "w") as f:
            f.write(format_markdown(report, top=args.top))
    try:
        if args.json_out == "-":
            # Stdout-JSON mode: stdout must be PURE JSON (pipeable into
            # jq); the human render goes to stderr instead.
            print(format_markdown(report, top=args.top), file=sys.stderr)
            print(json.dumps(report, indent=2))
        else:
            print(format_markdown(report, top=args.top))
    except BrokenPipeError:
        # Downstream pager/head closed the pipe; the artifacts are on
        # disk, which is the contract. Exit clean, not with a traceback.
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
