"""Trace-timeline analyzer: from Chrome-trace JSON to machine verdicts.

PR 3 made the stack *emit* spans (``--trace-out`` on every driver and the
bench); this module *reads* them. Given one trace artifact it answers the
questions the raw Perfetto view leaves to eyeballing:

* **Critical path** — which (cat, name) owns each instant of wall clock.
  A sweep line walks every elementary interval between span boundaries and
  attributes it to the *innermost* open span (max nesting depth; ties to
  the latest-started span, then highest tid — deterministic). Attributed
  ("owned") shares therefore PARTITION the wall: they sum to ≤ 1.0 by
  construction, with the remainder reported as ``idle``. This is the table
  that names the bottleneck stage.
* **Wall-clock share per layer** — the union of each ``cat``'s span
  intervals over the trace wall. Unlike owned shares these may overlap
  across layers (that overlap is the point — see below), so they do NOT
  sum to 1.
* **Queue-wait breakdown** — aggregate of the explicit wait spans
  (``serve.queue_wait`` and anything else matching ``*queue_wait*``):
  count, total, mean, max per name.
* **Overlap report** — the measured answer to ROADMAP item 4's
  "ingest no longer serializing with compute" claim: the fraction of
  device-compute time (``optim``/``descent`` spans by default) during
  which an ``ingest`` span is concurrently open, plus the dual (fraction
  of ingest hidden under compute). A fully pipelined data path pushes the
  first number toward 1; today's serialize-then-solve path reads ~0.

Robustness contract (tested in tests/test_analysis.py): unclosed ``B``
events from crashed runs are clamped to the trace end and flagged (never a
negative duration), negative ``dur`` values are clamped to 0 and counted
in ``warnings``, zero-length traces produce an empty report instead of a
crash, and spans whose intervals straddle other threads' spans (the
micro-batcher's cross-thread queue-wait spans) are handled by the sweep
line like any other interval.
"""
from __future__ import annotations

import dataclasses
import heapq
import json
from typing import Iterable, Mapping, Optional, Sequence

__all__ = [
    "Span",
    "TimelineReport",
    "TraceParseError",
    "analyze_trace",
    "analyze_events",
    "load_trace",
]

# Layers treated as "device compute" / "ingest" for the overlap report.
DEFAULT_COMPUTE_CATS = frozenset({"optim", "descent"})
DEFAULT_INGEST_CATS = frozenset({"ingest"})

# Fraction below which ingest/compute are called serialized outright.
SERIALIZED_BELOW = 0.05
OVERLAPPED_ABOVE = 0.80


class TraceParseError(ValueError):
    """The artifact is not a readable Chrome trace-event document."""


@dataclasses.dataclass
class Span:
    """One complete span, times in seconds relative to the trace clock."""

    name: str
    cat: str
    start: float
    dur: float
    pid: int
    tid: int
    args: dict
    unclosed: bool = False
    depth: int = 0

    @property
    def end(self) -> float:
        return self.start + self.dur


def load_trace(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise TraceParseError(f"{path}: {e}") from e
    except ValueError as e:
        raise TraceParseError(f"{path}: not valid JSON ({e})") from e
    if isinstance(doc, list):  # bare event-array form is legal Chrome trace
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise TraceParseError(f"{path}: no traceEvents array")
    return doc


def parse_events(
    events: Iterable[Mapping],
) -> tuple[list[Span], list[dict], list[str]]:
    """Events → (spans, instants, warnings).

    Accepts the collector's ``X`` (complete) events plus ``B``/``E`` pairs
    from foreign tools; an unmatched ``B`` (crashed run) becomes a span
    clamped to the trace end, flagged ``unclosed``.
    """
    spans: list[Span] = []
    instants: list[dict] = []
    warnings: list[str] = []
    open_stacks: dict[tuple, list] = {}  # (pid, tid) -> [B events]
    max_ts = 0.0
    for e in events:
        if not isinstance(e, Mapping) or "ph" not in e or "ts" not in e:
            warnings.append(f"malformed event skipped: {e!r}")
            continue
        ph = e["ph"]
        try:
            ts = float(e["ts"]) / 1e6
        except (TypeError, ValueError):
            warnings.append(f"non-numeric ts skipped: {e!r}")
            continue
        pid = int(e.get("pid", 0))
        tid = int(e.get("tid", 0))
        if ph == "X":
            try:
                dur = float(e.get("dur", 0.0)) / 1e6
            except (TypeError, ValueError):
                dur = 0.0
                warnings.append(f"non-numeric dur clamped to 0: {e!r}")
            if dur < 0:
                warnings.append(
                    f"negative dur clamped to 0: {e.get('name')!r} ({dur})"
                )
                dur = 0.0
            spans.append(Span(
                name=str(e.get("name", "?")), cat=str(e.get("cat", "")),
                start=ts, dur=dur, pid=pid, tid=tid,
                args=dict(e.get("args") or {}),
            ))
            max_ts = max(max_ts, ts + dur)
        elif ph == "B":
            open_stacks.setdefault((pid, tid), []).append(e)
            max_ts = max(max_ts, ts)
        elif ph == "E":
            stack = open_stacks.get((pid, tid))
            if not stack:
                warnings.append(f"unmatched E event skipped: {e.get('name')!r}")
                continue
            b = stack.pop()
            b_ts = float(b["ts"]) / 1e6
            dur = ts - b_ts
            if dur < 0:
                warnings.append(
                    f"E before B clamped to 0: {b.get('name')!r}")
                dur = 0.0
            spans.append(Span(
                name=str(b.get("name", "?")), cat=str(b.get("cat", "")),
                start=b_ts, dur=dur, pid=pid, tid=tid,
                args=dict(b.get("args") or {}),
            ))
            max_ts = max(max_ts, ts)
        elif ph == "i":
            instants.append(dict(e))
            max_ts = max(max_ts, ts)
        # other phases (M metadata, counters) are ignored
    # Unclosed B events: a crashed run never wrote the E. Clamp to the
    # trace end so the span exists with a NON-NEGATIVE duration, flagged.
    for (pid, tid), stack in open_stacks.items():
        for b in stack:
            b_ts = float(b["ts"]) / 1e6
            warnings.append(
                f"unclosed span clamped to trace end: {b.get('name')!r}")
            spans.append(Span(
                name=str(b.get("name", "?")), cat=str(b.get("cat", "")),
                start=b_ts, dur=max(0.0, max_ts - b_ts), pid=pid, tid=tid,
                args=dict(b.get("args") or {}), unclosed=True,
            ))
    return spans, instants, warnings


def _assign_depths(spans: Sequence[Span]) -> None:
    """Nesting depth per (pid, tid) lane (innermost = deepest)."""
    lanes: dict[tuple, list[Span]] = {}
    for s in spans:
        lanes.setdefault((s.pid, s.tid), []).append(s)
    for lane in lanes.values():
        lane.sort(key=lambda s: (s.start, -s.dur))
        stack: list[Span] = []
        for s in lane:
            while stack and stack[-1].end <= s.start + 1e-12:
                stack.pop()
            s.depth = len(stack)
            stack.append(s)


def _union_seconds(intervals: Iterable[tuple[float, float]]) -> float:
    ivs = sorted(i for i in intervals if i[1] > i[0])
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in ivs:
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


def _intersection_seconds(
    a: Iterable[tuple[float, float]], b: Iterable[tuple[float, float]]
) -> float:
    """|union(a) ∩ union(b)| via a two-pointer merge of the unions."""

    def merged(ivs):
        out = []
        for lo, hi in sorted(i for i in ivs if i[1] > i[0]):
            if out and lo <= out[-1][1]:
                out[-1][1] = max(out[-1][1], hi)
            else:
                out.append([lo, hi])
        return out

    ma, mb = merged(a), merged(b)
    i = j = 0
    total = 0.0
    while i < len(ma) and j < len(mb):
        lo = max(ma[i][0], mb[j][0])
        hi = min(ma[i][1], mb[j][1])
        if hi > lo:
            total += hi - lo
        if ma[i][1] <= mb[j][1]:
            i += 1
        else:
            j += 1
    return total


def _attribute_wall(spans: Sequence[Span]) -> dict[tuple[str, str], float]:
    """Sweep line: every elementary interval goes to the innermost open
    span — (depth, start, tid) max, one owner per instant — so the owned
    totals partition the busy wall exactly."""
    timed = [s for s in spans if s.dur > 0]
    if not timed:
        return {}
    bounds = sorted({s.start for s in timed} | {s.end for s in timed})
    by_start = sorted(timed, key=lambda s: s.start)
    owned: dict[tuple[str, str], float] = {}
    open_spans: dict[int, Span] = {}
    end_heap: list[tuple[float, int]] = []
    nxt = 0
    for k in range(len(bounds) - 1):
        seg_lo, seg_hi = bounds[k], bounds[k + 1]
        while nxt < len(by_start) and by_start[nxt].start <= seg_lo + 1e-12:
            s = by_start[nxt]
            open_spans[id(s)] = s
            heapq.heappush(end_heap, (s.end, id(s)))
            nxt += 1
        while end_heap and end_heap[0][0] <= seg_lo + 1e-12:
            _, sid = heapq.heappop(end_heap)
            open_spans.pop(sid, None)
        if open_spans:
            owner = max(
                open_spans.values(),
                key=lambda s: (s.depth, s.start, s.tid),
            )
            key = (owner.cat, owner.name)
            owned[key] = owned.get(key, 0.0) + (seg_hi - seg_lo)
    return owned


@dataclasses.dataclass
class TimelineReport:
    """Everything the analyzer derives from one trace artifact."""

    wall_seconds: float
    n_spans: int
    n_instants: int
    # (cat, name) -> owned wall seconds (partition; sums to <= wall)
    owned: dict
    idle_seconds: float
    # cat -> {"busy_seconds", "busy_share", "owned_seconds", "owned_share",
    #         "spans"}
    layers: dict
    # name -> {"count", "total_s", "mean_ms", "max_ms"}
    queue_wait: dict
    # overlap report (None values when either side has no spans)
    overlap: dict
    warnings: list
    unclosed_spans: int

    @property
    def owned_shares(self) -> dict:
        if self.wall_seconds <= 0:
            return {}
        return {
            f"{cat}:{name}": secs / self.wall_seconds
            for (cat, name), secs in self.owned.items()
        }

    def critical_path(self, top: int = 12) -> list[dict]:
        """Owned-wall table rows, biggest owner first."""
        rows = sorted(
            self.owned.items(), key=lambda kv: kv[1], reverse=True
        )[:top]
        wall = self.wall_seconds or 1.0
        return [
            {"cat": cat, "name": name, "owned_s": round(secs, 6),
             "share": round(secs / wall, 4)}
            for (cat, name), secs in rows
        ]

    def bottleneck(self) -> Optional[dict]:
        cp = self.critical_path(top=1)
        return cp[0] if cp else None

    def to_dict(self) -> dict:
        return {
            "schema": "photon-timeline/1",
            "wall_seconds": round(self.wall_seconds, 6),
            "n_spans": self.n_spans,
            "n_instants": self.n_instants,
            "unclosed_spans": self.unclosed_spans,
            "idle_seconds": round(self.idle_seconds, 6),
            "critical_path": self.critical_path(),
            "layers": self.layers,
            "queue_wait": self.queue_wait,
            "overlap": self.overlap,
            "warnings": self.warnings,
        }

    def format_text(self, top: int = 12) -> str:
        lines = [
            f"trace wall: {self.wall_seconds * 1e3:.2f} ms, "
            f"{self.n_spans} spans, {self.n_instants} instants"
            + (f", {self.unclosed_spans} UNCLOSED (crashed run?)"
               if self.unclosed_spans else ""),
            "",
            "critical path (owned wall share; innermost span owns each "
            "instant):",
            f"  {'share':>7}  {'owned':>10}  span",
        ]
        for row in self.critical_path(top):
            lines.append(
                f"  {row['share'] * 100:6.1f}%  "
                f"{row['owned_s'] * 1e3:8.2f}ms  "
                f"{row['cat']}:{row['name']}"
            )
        if self.wall_seconds > 0:
            lines.append(
                f"  {self.idle_seconds / self.wall_seconds * 100:6.1f}%  "
                f"{self.idle_seconds * 1e3:8.2f}ms  (idle: no span open)"
            )
        lines += ["", "per-layer wall share (unions; may overlap):"]
        for cat, d in sorted(self.layers.items(),
                             key=lambda kv: -kv[1]["busy_seconds"]):
            lines.append(
                f"  {cat:<10} busy {d['busy_share'] * 100:5.1f}%  "
                f"owned {d['owned_share'] * 100:5.1f}%  "
                f"({d['spans']} spans)"
            )
        if self.queue_wait:
            lines += ["", "queue-wait breakdown:"]
            for name, d in sorted(self.queue_wait.items()):
                lines.append(
                    f"  {name}: {d['count']} waits, total "
                    f"{d['total_s'] * 1e3:.2f}ms, mean {d['mean_ms']:.3f}ms, "
                    f"max {d['max_ms']:.3f}ms"
                )
        ov = self.overlap
        lines += ["", "ingest/compute overlap:"]
        if ov.get("compute_overlapped_fraction") is None:
            lines.append("  n/a (no "
                         + ("compute" if ov.get("compute_busy_s") in (0, None)
                            else "ingest")
                         + " spans in this trace)")
        else:
            lines.append(
                f"  compute busy {ov['compute_busy_s'] * 1e3:.2f}ms, ingest "
                f"busy {ov['ingest_busy_s'] * 1e3:.2f}ms, concurrent "
                f"{ov['overlap_s'] * 1e3:.2f}ms"
            )
            lines.append(
                f"  fraction of compute with ingest concurrently open: "
                f"{ov['compute_overlapped_fraction']:.4f}  -> "
                f"{ov['verdict']}"
            )
            lines.append(
                f"  fraction of ingest hidden under compute: "
                f"{ov['ingest_hidden_fraction']:.4f}"
            )
        if self.warnings:
            lines += ["", f"warnings ({len(self.warnings)}):"]
            lines += [f"  {w}" for w in self.warnings[:10]]
            if len(self.warnings) > 10:
                lines.append(f"  ... {len(self.warnings) - 10} more")
        return "\n".join(lines)


def analyze_events(
    events: Iterable[Mapping],
    compute_cats: frozenset = DEFAULT_COMPUTE_CATS,
    ingest_cats: frozenset = DEFAULT_INGEST_CATS,
) -> TimelineReport:
    spans, instants, warnings = parse_events(events)
    if not spans:
        return TimelineReport(
            wall_seconds=0.0, n_spans=0, n_instants=len(instants),
            owned={}, idle_seconds=0.0, layers={}, queue_wait={},
            overlap={"compute_busy_s": None, "ingest_busy_s": None,
                     "overlap_s": None,
                     "compute_overlapped_fraction": None,
                     "ingest_hidden_fraction": None, "verdict": "empty"},
            warnings=warnings, unclosed_spans=0,
        )
    _assign_depths(spans)
    t_lo = min(s.start for s in spans)
    t_hi = max(s.end for s in spans)
    wall = max(0.0, t_hi - t_lo)
    owned = _attribute_wall(spans)
    idle = max(0.0, wall - sum(owned.values()))

    layers: dict[str, dict] = {}
    for cat in {s.cat for s in spans}:
        cat_spans = [s for s in spans if s.cat == cat]
        busy = _union_seconds((s.start, s.end) for s in cat_spans)
        owned_cat = sum(v for (c, _), v in owned.items() if c == cat)
        layers[cat] = {
            "busy_seconds": round(busy, 6),
            "busy_share": round(busy / wall, 4) if wall else 0.0,
            "owned_seconds": round(owned_cat, 6),
            "owned_share": round(owned_cat / wall, 4) if wall else 0.0,
            "spans": len(cat_spans),
        }

    queue_wait: dict[str, dict] = {}
    for s in spans:
        if "queue_wait" not in s.name:
            continue
        d = queue_wait.setdefault(
            s.name, {"count": 0, "total_s": 0.0, "max_ms": 0.0})
        d["count"] += 1
        d["total_s"] += s.dur
        d["max_ms"] = max(d["max_ms"], s.dur * 1e3)
    for d in queue_wait.values():
        d["mean_ms"] = round(d["total_s"] * 1e3 / d["count"], 3)
        d["total_s"] = round(d["total_s"], 6)
        d["max_ms"] = round(d["max_ms"], 3)

    compute_ivs = [(s.start, s.end) for s in spans if s.cat in compute_cats]
    ingest_ivs = [(s.start, s.end) for s in spans if s.cat in ingest_cats]
    compute_busy = _union_seconds(compute_ivs)
    ingest_busy = _union_seconds(ingest_ivs)
    if compute_busy > 0 and ingest_busy > 0:
        both = _intersection_seconds(compute_ivs, ingest_ivs)
        frac = both / compute_busy
        verdict = (
            "serialized" if frac < SERIALIZED_BELOW
            else "overlapped" if frac > OVERLAPPED_ABOVE
            else "partially-overlapped"
        )
        overlap = {
            "compute_busy_s": round(compute_busy, 6),
            "ingest_busy_s": round(ingest_busy, 6),
            "overlap_s": round(both, 6),
            "compute_overlapped_fraction": round(frac, 4),
            "ingest_hidden_fraction": round(both / ingest_busy, 4),
            "verdict": verdict,
        }
    else:
        overlap = {
            "compute_busy_s": round(compute_busy, 6),
            "ingest_busy_s": round(ingest_busy, 6),
            "overlap_s": None,
            "compute_overlapped_fraction": None,
            "ingest_hidden_fraction": None,
            "verdict": "one-sided" if (compute_busy or ingest_busy)
            else "empty",
        }

    return TimelineReport(
        wall_seconds=wall, n_spans=len(spans), n_instants=len(instants),
        owned=owned, idle_seconds=idle, layers=layers,
        queue_wait=queue_wait, overlap=overlap, warnings=warnings,
        unclosed_spans=sum(1 for s in spans if s.unclosed),
    )


def analyze_trace(path: str, **kw) -> TimelineReport:
    """Load one ``--trace-out`` artifact and analyze it."""
    return analyze_events(load_trace(path)["traceEvents"], **kw)


def roofline_attribution(
    report: TimelineReport, bench_details: Mapping
) -> dict:
    """Join the bench roofline numbers with the timeline: name the stage
    that owns the gap. ``bench_details`` is a BENCH_DETAILS*-shaped dict
    (see ``obs.analysis.artifacts.load_bench_details``)."""
    roof = (bench_details or {}).get("roofline") or {}
    bn = report.bottleneck()
    ov = report.overlap.get("compute_overlapped_fraction")
    out = {
        "fraction_of_roofline": roof.get("fraction_of_roofline"),
        "roofline_backend": roof.get("backend"),
        "bottleneck": f"{bn['cat']}:{bn['name']}" if bn else None,
        "bottleneck_share": bn["share"] if bn else None,
        "ingest_compute_overlap": ov,
    }
    frac = roof.get("fraction_of_roofline")
    if frac is not None and bn is not None:
        out["note"] = (
            f"fraction_of_roofline={frac}: the headline pass runs at "
            f"{frac:.0%} of the memory roofline; the timeline says "
            f"{bn['cat']}:{bn['name']} owns {bn['share']:.0%} of wall"
            + (f" and ingest/compute overlap is {ov:.2f} "
               f"({report.overlap.get('verdict')})" if ov is not None
               else " (no ingest/compute overlap measurable in this trace)")
            + "."
        )
    return out
