"""Declarative SLOs evaluated against metrics snapshots.

The missing half of the PR-3 metrics layer: counters and histograms are
exported, but nothing *judges* them. An :class:`SloConfig` is a small JSON
document of rules; evaluating one against any metrics snapshot (a serving
``metrics_snapshot()``, the process-global ``REGISTRY.snapshot()``, or the
bench's details dict) produces a pass/fail report and — the observable
contract — emits one ``slo.pass`` / ``slo.violation`` instant per rule
into the active trace and bumps the process-global
``slo_violations_total{slo=...}`` counter per violation, so SLO state
rides the same Prometheus scrape and Chrome-trace timeline as everything
else.

Config schema (docs/observability.md §SLO)::

    {"slos": [
      {"name": "serve_p99",
       "metric": "latency.p99_ms",        # dotted path into the snapshot
       "op": "<=",                        # <=, <, >=, >, ==, !=
       "threshold": 50.0,
       "description": "p99 under 50ms",   # optional
       "on_missing": "skip"}              # or "violate"; default skip
    ]}

``metric`` paths resolve dict-by-dict; when the resolved value is itself
a dict (a labeled counter like ``kernel_retraces_after_warmup_total``'s
per-kernel map, or a histogram snapshot), its numeric leaves are SUMMED —
so ``{"metric": "kernel_retraces_after_warmup_total", "op": "==",
"threshold": 0}`` expresses "no retraces after warmup, on any kernel".
A rule whose metric is absent from the snapshot being evaluated is
``skipped`` by default (one config can carry serving rules and bench
rules; each evaluation judges the rules it can see) — set
``on_missing: "violate"`` for rules where silence is itself a failure.

Evaluation points wired in this PR: the serving server's periodic metrics
flush + shutdown (``ScoringServer(slo_config=...)``), the supervisor
heartbeat (:class:`SloWatchdog` riding :class:`supervisor.Heartbeat`),
and the bench (``--slo-config``: the serve stage evaluates against the
live server snapshot, the end of the run against the details artifact).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import operator
import time
from typing import Callable, Mapping, Optional, Sequence

from photon_tpu.obs.metrics import MetricsRegistry, REGISTRY
from photon_tpu.obs.trace import instant

__all__ = [
    "SloConfigError",
    "SloRule",
    "SloResult",
    "SloReport",
    "SloConfig",
    "SloWatchdog",
    "VIOLATIONS_COUNTER",
]

VIOLATIONS_COUNTER = "slo_violations_total"

_OPS: dict[str, Callable[[float, float], bool]] = {
    "<=": operator.le,
    "<": operator.lt,
    ">=": operator.ge,
    ">": operator.gt,
    "==": operator.eq,
    "!=": operator.ne,
}

_log = logging.getLogger("photon_tpu.obs.slo")


class SloConfigError(ValueError):
    """The SLO config document violates the schema."""


@dataclasses.dataclass(frozen=True)
class SloRule:
    name: str
    metric: str
    op: str
    threshold: float
    description: str = ""
    on_missing: str = "skip"  # "skip" | "violate"

    @classmethod
    def from_dict(cls, d: Mapping) -> "SloRule":
        if not isinstance(d, Mapping):
            raise SloConfigError(f"rule must be an object, got {d!r}")
        missing = [k for k in ("name", "metric", "op", "threshold")
                   if k not in d]
        if missing:
            raise SloConfigError(
                f"rule {d.get('name', d)!r} missing keys: {missing}")
        if d["op"] not in _OPS:
            raise SloConfigError(
                f"rule {d['name']!r}: unknown op {d['op']!r} "
                f"(allowed: {sorted(_OPS)})")
        try:
            threshold = float(d["threshold"])
        except (TypeError, ValueError):
            raise SloConfigError(
                f"rule {d['name']!r}: threshold {d['threshold']!r} "
                f"is not a number")
        on_missing = d.get("on_missing", "skip")
        if on_missing not in ("skip", "violate"):
            raise SloConfigError(
                f"rule {d['name']!r}: on_missing must be 'skip' or "
                f"'violate', got {on_missing!r}")
        return cls(
            name=str(d["name"]), metric=str(d["metric"]), op=str(d["op"]),
            threshold=threshold, description=str(d.get("description", "")),
            on_missing=on_missing,
        )


def _resolve(snapshot: Mapping, path: str):
    """Dotted lookup; dict leaves sum their numeric values; None if the
    path (or any numeric interpretation of its leaf) is absent."""
    cur = snapshot
    for part in path.split("."):
        if not isinstance(cur, Mapping) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool):
        return float(cur)
    if isinstance(cur, (int, float)):
        return float(cur)
    if isinstance(cur, Mapping):
        vals = [v for v in cur.values()
                if isinstance(v, (int, float)) and not isinstance(v, bool)]
        return float(sum(vals)) if vals else None
    return None


@dataclasses.dataclass
class SloResult:
    name: str
    metric: str
    op: str
    threshold: float
    value: Optional[float]
    status: str  # "pass" | "violation" | "skipped"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SloReport:
    where: str
    results: list

    @property
    def violations(self) -> list:
        return [r for r in self.results if r.status == "violation"]

    @property
    def checked(self) -> int:
        return sum(1 for r in self.results if r.status != "skipped")

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "where": self.where,
            "ok": self.ok,
            "checked": self.checked,
            "violations": [r.name for r in self.violations],
            "results": [r.to_dict() for r in self.results],
        }


class SloConfig:
    """A parsed set of :class:`SloRule`\\ s."""

    def __init__(self, rules: Sequence[SloRule]):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise SloConfigError(f"duplicate rule names: {sorted(dupes)}")

    @classmethod
    def from_dict(cls, doc: Mapping) -> "SloConfig":
        if not isinstance(doc, Mapping) or not isinstance(
                doc.get("slos"), list):
            raise SloConfigError(
                'SLO config must be {"slos": [rule, ...]}')
        return cls([SloRule.from_dict(r) for r in doc["slos"]])

    @classmethod
    def from_file(cls, path: str) -> "SloConfig":
        try:
            with open(path) as f:
                doc = json.load(f)
        except OSError as e:
            raise SloConfigError(f"{path}: {e}") from e
        except ValueError as e:
            raise SloConfigError(f"{path}: not valid JSON ({e})") from e
        return cls.from_dict(doc)

    def evaluate(
        self,
        snapshot: Mapping,
        where: str = "",
        registry: Optional[MetricsRegistry] = None,
        emit: bool = True,
    ) -> SloReport:
        """Judge every rule against ``snapshot``.

        ``emit=True`` (the default) produces the observable side effects:
        a ``slo.pass``/``slo.violation`` trace instant per judged rule,
        a ``slo_violations_total{slo=...}`` bump per violation (in
        ``registry``, default the process-global one), and a log warning
        naming the rule. ``emit=False`` is the pure-judgment mode the
        analyzer CLI and tests use."""
        reg = REGISTRY if registry is None else registry
        results = []
        for rule in self.rules:
            value = _resolve(snapshot, rule.metric)
            if value is None:
                status = ("violation" if rule.on_missing == "violate"
                          else "skipped")
            else:
                status = ("pass" if _OPS[rule.op](value, rule.threshold)
                          else "violation")
            results.append(SloResult(
                name=rule.name, metric=rule.metric, op=rule.op,
                threshold=rule.threshold, value=value, status=status,
            ))
            if not emit or status == "skipped":
                continue
            if status == "violation":
                reg.counter(
                    VIOLATIONS_COUNTER,
                    "SLO rule violations observed at evaluation points "
                    "(serving flush, heartbeat, bench end)",
                ).inc(slo=rule.name)
                instant(
                    "slo.violation", cat="slo", slo=rule.name,
                    metric=rule.metric, op=rule.op,
                    threshold=rule.threshold, value=value, where=where,
                )
                _log.warning(
                    "SLO violation [%s]%s: %s = %s, want %s %s%s",
                    rule.name, f" at {where}" if where else "",
                    rule.metric, value, rule.op, rule.threshold,
                    f" ({rule.description})" if rule.description else "",
                )
            else:
                instant(
                    "slo.pass", cat="slo", slo=rule.name,
                    metric=rule.metric, value=value, where=where,
                )
        return SloReport(where=where, results=results)


class SloWatchdog:
    """Periodic SLO evaluation against a live snapshot source.

    Built to ride :class:`supervisor.Heartbeat`'s beat loop (pass one as
    ``Heartbeat(slo_watchdog=...)``): each ``check()`` call evaluates at
    most once per ``min_interval_s`` (0 = every call) so a fast beat
    interval doesn't turn every beat into an evaluation. Snapshot source
    defaults to the process-global registry."""

    def __init__(
        self,
        config: SloConfig,
        snapshot_fn: Optional[Callable[[], Mapping]] = None,
        where: str = "heartbeat",
        min_interval_s: float = 0.0,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.config = config
        self.snapshot_fn = (
            snapshot_fn if snapshot_fn is not None else REGISTRY.snapshot
        )
        self.where = where
        self.min_interval_s = float(min_interval_s)
        self.registry = registry
        self.last_report: Optional[SloReport] = None
        self._last_eval = 0.0

    def check(self) -> Optional[SloReport]:
        now = time.monotonic()
        if self._last_eval and now - self._last_eval < self.min_interval_s:
            return None
        self._last_eval = now
        try:
            snapshot = self.snapshot_fn()
        except Exception as e:  # noqa: BLE001 - a sick probe must not kill
            _log.warning("SLO snapshot source failed: %s", e)  # the beat loop
            return None
        self.last_report = self.config.evaluate(
            snapshot, where=self.where, registry=self.registry)
        return self.last_report
