"""Timeline-analyzer + fleet-report CLI (docs/observability.md).

    python -m photon_tpu.obs.analysis run-trace.json
    python -m photon_tpu.obs.analysis bench-trace.json \\
        --bench BENCH_DETAILS.json --json report.json
    python -m photon_tpu.obs.analysis report <run-dir> --json report.json

The bare form prints one trace's critical-path table, per-layer wall
shares, the queue-wait breakdown, and the ingest/compute overlap
fraction; ``--bench`` joins the bench roofline numbers to name the
bottleneck stage. The ``report`` subcommand fuses a MULTI-process run's
telemetry — merged trace shards, registry shards, metrics JSONL,
recovery journals, bench artifacts, anomaly scan — into one fleet report
(``obs/analysis/report.py``; docs/observability.md §"Fleet view").
Exit 2 on a malformed trace, 0 otherwise (the analyzer reports, it does
not gate — gating lives in scripts/bench_compare.py and the SLO configs).
"""
from __future__ import annotations

import argparse
import json
import sys

from photon_tpu.obs.analysis.artifacts import (
    ArtifactError,
    load_bench_details,
)
from photon_tpu.obs.analysis.timeline import (
    TraceParseError,
    analyze_trace,
    roofline_attribution,
)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "report":
        from photon_tpu.obs.analysis.report import main as report_main

        return report_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m photon_tpu.obs.analysis",
        description="Analyze a --trace-out Chrome-trace artifact.",
    )
    ap.add_argument("trace", help="trace JSON written via --trace-out")
    ap.add_argument("--bench", default=None,
                    help="bench artifact (BENCH_DETAILS*.json / BENCH_r*."
                         "json) to join for roofline attribution")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="also write the full report as JSON to this path "
                         "('-' for stdout)")
    ap.add_argument("--top", type=int, default=12,
                    help="critical-path rows to print (default 12)")
    args = ap.parse_args(argv)

    try:
        report = analyze_trace(args.trace)
    except TraceParseError as e:
        print(f"analysis: schema error: {e}", file=sys.stderr)
        return 2

    print(report.format_text(top=args.top))

    doc = report.to_dict()
    if args.bench:
        try:
            details = load_bench_details(args.bench)
        except ArtifactError as e:
            print(f"analysis: schema error: {e}", file=sys.stderr)
            return 2
        attribution = roofline_attribution(report, details)
        doc["roofline_attribution"] = attribution
        print("\nroofline attribution:")
        for k, v in attribution.items():
            print(f"  {k}: {v}")

    if args.json_out == "-":
        print(json.dumps(doc, indent=2))
    elif args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(doc, f, indent=2)
        print(f"\nreport written to {args.json_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
