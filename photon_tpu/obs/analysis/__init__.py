"""Analysis layer over the PR-3 observability artifacts: the consumers.

The instrumentation layer (``photon_tpu/obs/``) emits three artifact
families — Chrome-trace timelines (``--trace-out``), metrics snapshots
(JSONL / Prometheus), and bench details (``BENCH_DETAILS*.json``). This
package turns them into decisions:

* ``timeline``      — span-tree / critical-path / queue-wait / overlap
  analyzer for trace artifacts; CLI at
  ``python -m photon_tpu.obs.analysis <trace.json>``.
* ``artifacts``     — bench-artifact loading + per-metric backend
  attribution (the comparability rules).
* ``bench_compare`` — backend-aware regression gate; CLI at
  ``scripts/bench_compare.py`` (advisory ci.sh stage).
* ``slo``           — declarative SLO rules evaluated against metrics
  snapshots (serving flush, supervisor heartbeat, bench end), emitting
  trace instants and ``slo_violations_total``.

docs/observability.md §"Reading the telemetry" documents all three CLIs
and schemas.
"""
from photon_tpu.obs.analysis.artifacts import (
    ArtifactError,
    BenchArtifact,
    flatten_metrics,
    load_bench_artifact,
    load_bench_details,
    metric_backend,
    newest_artifacts,
    normalize_backend,
)
from photon_tpu.obs.analysis.bench_compare import (
    compare_artifacts,
    compare_pair,
    format_verdict,
    metric_direction,
)
from photon_tpu.obs.analysis.slo import (
    SloConfig,
    SloConfigError,
    SloReport,
    SloRule,
    SloWatchdog,
)
from photon_tpu.obs.analysis.report import (
    REPORT_SCHEMA,
    anomaly_scan,
    build_report,
    detect_level_shifts,
    format_markdown,
)
from photon_tpu.obs.analysis.timeline import (
    Span,
    TimelineReport,
    TraceParseError,
    analyze_events,
    analyze_trace,
    load_trace,
    roofline_attribution,
)

__all__ = [
    "REPORT_SCHEMA",
    "anomaly_scan",
    "build_report",
    "detect_level_shifts",
    "format_markdown",
    "ArtifactError",
    "BenchArtifact",
    "Span",
    "SloConfig",
    "SloConfigError",
    "SloReport",
    "SloRule",
    "SloWatchdog",
    "TimelineReport",
    "TraceParseError",
    "analyze_events",
    "analyze_trace",
    "compare_artifacts",
    "compare_pair",
    "flatten_metrics",
    "format_verdict",
    "load_bench_artifact",
    "load_bench_details",
    "load_trace",
    "metric_backend",
    "metric_direction",
    "newest_artifacts",
    "normalize_backend",
    "roofline_attribution",
]
