"""Fleet observability: cross-process telemetry aggregation.

The instrumentation layer (trace spans, metrics registry, recovery
journal) is strictly per-process; the reproduction's topology is not —
a supervised training driver, N serving workers, an online trainer
publishing over HTTP, mesh hosts with per-host cost tables. Upstream
photon-ml gets cluster-wide visibility for free from the Spark driver UI;
this module is the rebuild's equivalent substrate
(docs/observability.md §"Fleet view"):

* **Trace-shard merging** — :func:`merge_traces` combines N per-process
  ``--trace-out`` files into ONE Perfetto-loadable timeline. Each shard's
  :data:`obs.trace.ANCHOR_EVENT` (stamped at collector install) carries
  the wall-clock ↔ ``perf_counter`` correspondence, so the merger aligns
  clocks by wall time (per-process ``perf_counter`` origins are arbitrary
  and wildly skewed — the anchor is what makes shards comparable),
  assigns stable process lanes (colliding pids across hosts are
  remapped), and preserves cross-process trace-id joins — the online
  event→refresh→publish→served-score chain becomes one visible flow.
  Anchor-less shards (traces written before the anchor contract) are
  REFUSED with a clear error; single-trace analysis of them still works.

* **Metrics shard export/collect** — :func:`write_registry_shard` dumps a
  process's registry state (full histogram bins, not just quantiles) as
  one JSON file; :func:`collect_shards` folds any number of them through
  ``MetricsRegistry.merge`` (counters sum, gauges latest-by-anchor,
  histograms merge bins; per-``shard_id`` idempotence, so a
  double-collected shard changes nothing) into one fleet registry with
  JSON *and* Prometheus exposition.

* **Journal merging** — :func:`merge_journals` interleaves recovery /
  patch journals from all attempts and processes into one causally
  ordered stream (sub-second ``t`` stamps when present, ISO ``time``
  fallback for rows written before the stamp existed).

* **Run-dir discovery** — :func:`discover` maps the ``--telemetry-dir``
  shard layout (plus driver output dirs nested under a run root) to the
  artifact families the run-report CLI (``obs/analysis/report.py``)
  fuses.
"""
from __future__ import annotations

import calendar
import dataclasses
import glob
import json
import os
import time
from typing import Iterable, Mapping, Optional, Sequence

from photon_tpu.obs import trace as trace_mod
from photon_tpu.obs.metrics import REGISTRY, MetricsRegistry

__all__ = [
    "FLEET_TRACE_SCHEMA",
    "SHARD_SCHEMA",
    "FleetMergeError",
    "FleetRunFiles",
    "collect_shards",
    "cross_process_joins",
    "discover",
    "find_anchor",
    "load_registry_shard",
    "load_trace_shard",
    "merge_journals",
    "merge_traces",
    "write_registry_shard",
]

FLEET_TRACE_SCHEMA = "photon-fleet-trace/1"
SHARD_SCHEMA = "photon-registry-shard/1"


class FleetMergeError(ValueError):
    """A shard cannot participate in a fleet merge (missing anchor,
    unreadable file, wrong schema). ``merged_doc`` is True when the file
    is itself a merge OUTPUT (a ``photon.fleet`` document) — merging it
    again would double-count every shard it already contains."""

    def __init__(self, msg: str, merged_doc: bool = False):
        super().__init__(msg)
        self.merged_doc = merged_doc


# ------------------------------------------------------------ trace merge


def find_anchor(events: Iterable[Mapping]) -> Optional[dict]:
    """The shard's anchor event (``{"ts": ..., **args}``), or None.

    The anchor maps any event timestamp in the shard to wall time:
    ``wall(ts) = anchor["wall_time"] + (ts - anchor["ts"]) / 1e6``.
    """
    for e in events:
        if (isinstance(e, Mapping) and e.get("name") == trace_mod.ANCHOR_EVENT
                and e.get("ph") in ("i", "I")):
            args = dict(e.get("args") or {})
            if "wall_time" not in args:
                continue
            try:
                return {"ts": float(e.get("ts", 0.0)), **args}
            except (TypeError, ValueError):
                continue
    return None


def load_trace_shard(path: str) -> tuple[list, dict]:
    """(events, anchor) for one shard; FleetMergeError names the file on
    a missing anchor or unreadable document."""
    from photon_tpu.obs.analysis.timeline import TraceParseError, load_trace

    try:
        doc = load_trace(path)
    except TraceParseError as e:
        raise FleetMergeError(str(e)) from e
    if isinstance(doc, Mapping) and "photon.fleet" in doc:
        # A previously-written merge OUTPUT (e.g. a --merged-trace file
        # left in the run dir): it carries its shards' anchors, so
        # re-merging it would silently double-count every span and
        # invent phantom processes in the topology.
        raise FleetMergeError(
            f"{path}: already a merged photon.fleet document — refusing "
            "to re-merge it as a shard", merged_doc=True)
    events = doc["traceEvents"]
    anchor = find_anchor(events)
    if anchor is None:
        raise FleetMergeError(
            f"{path}: no {trace_mod.ANCHOR_EVENT!r} metadata event — this "
            "trace predates the fleet-anchor contract (its process-local "
            "clock origin is unrecoverable), so it cannot be merged. "
            "Single-trace analysis still works: "
            f"python -m photon_tpu.obs.analysis {path}"
        )
    return events, anchor


def merge_traces(paths: Sequence[str],
                 out_path: Optional[str] = None) -> dict:
    """Merge N per-process trace shards into one wall-clock-aligned
    Chrome trace document.

    Every shard MUST carry an anchor (:class:`FleetMergeError` names the
    offending file otherwise). Timestamps are re-based so ``ts`` 0 is the
    earliest wall instant any shard's clock can express; events keep
    their original relative order per shard and interleave by wall time
    across shards (host wall-clock skew is not corrected — anchors are
    honest about what they stamp, and docs cover NTP expectations).
    Colliding pids (two hosts, same pid) get remapped lanes so Perfetto
    never folds two processes into one track.
    """
    if not paths:
        raise FleetMergeError("no trace shards to merge")
    shards = []
    for p in paths:
        events, anchor = load_trace_shard(p)
        # Wall time at this shard's ts=0 — the per-shard clock offset.
        wall0 = float(anchor["wall_time"]) - float(anchor["ts"]) / 1e6
        shards.append({"path": p, "events": events, "anchor": anchor,
                       "wall0": wall0})
    origin = min(s["wall0"] for s in shards)

    used_pids: set = set()
    merged: list[dict] = []
    shard_meta = []
    for i, s in enumerate(shards):
        pid = int(s["anchor"].get("pid", 0))
        lane = pid
        while lane in used_pids:
            # Stable, readable remap: keep the low digits recognizable.
            lane += 1_000_000
        used_pids.add(lane)
        shift_us = (s["wall0"] - origin) * 1e6
        n = 0
        for e in s["events"]:
            if not isinstance(e, Mapping) or "ts" not in e:
                continue
            try:
                ts = float(e["ts"]) + shift_us
            except (TypeError, ValueError):
                continue
            e2 = dict(e)
            e2["ts"] = round(ts, 1)
            e2["pid"] = lane
            merged.append(e2)
            n += 1
        shard_meta.append({
            "path": os.path.abspath(s["path"]),
            "role": s["anchor"].get("role", "unknown"),
            "hostname": s["anchor"].get("hostname", "unknown"),
            "pid": pid,
            "lane_pid": lane,
            "wall0": round(s["wall0"], 6),
            "events": n,
        })
    # Deterministic, Perfetto-friendly ordering (stable sort keeps each
    # shard's same-ts ties in emit order).
    merged.sort(key=lambda e: e["ts"])
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "photon.fleet": {
            "schema": FLEET_TRACE_SCHEMA,
            "origin_wall_time": origin,
            "shards": shard_meta,
        },
    }
    if out_path:
        d = os.path.dirname(out_path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return doc


def cross_process_joins(doc: Mapping, min_pids: int = 2) -> list[dict]:
    """Trace ids whose events span >= ``min_pids`` distinct process lanes
    in a merged document — the cross-process flows (e.g. the online
    trainer's publish trace id re-entering the serving process's
    /admin/patch handler). Sorted most-processes-first."""
    roles = {s["lane_pid"]: s["role"]
             for s in (doc.get("photon.fleet") or {}).get("shards", [])}
    by_id: dict[str, dict] = {}
    for e in doc.get("traceEvents", []):
        args = e.get("args") or {}
        tid = args.get("trace_id")
        if tid is None:
            continue
        d = by_id.setdefault(str(tid), {"pids": set(), "events": 0})
        d["pids"].add(int(e.get("pid", 0)))
        d["events"] += 1
    out = []
    for tid, d in by_id.items():
        if len(d["pids"]) >= min_pids:
            pids = sorted(d["pids"])
            out.append({
                "trace_id": tid,
                "pids": pids,
                "roles": sorted({roles.get(p, "unknown") for p in pids}),
                "events": d["events"],
            })
    out.sort(key=lambda j: (-len(j["pids"]), j["trace_id"]))
    return out


# ------------------------------------------------------- registry shards


def _shard_id(role: str, pid: int, hostname: str) -> str:
    return f"{hostname}:{pid}:{role}"


def write_registry_shard(
    path: str,
    registries: Optional[Sequence[MetricsRegistry]] = None,
    role: Optional[str] = None,
    extra: Optional[Mapping] = None,
) -> str:
    """Export this process's metrics state as one mergeable shard file.

    ``registries`` defaults to the process-global registry; pass extras
    (e.g. a ``ScoringServer.metrics``) to fold per-component registries
    into the same shard. Written atomically (tmp + replace) so a
    concurrent :func:`collect_shards` never reads a torn file.
    """
    import socket

    regs = list(registries) if registries else [REGISTRY]
    if not any(r is REGISTRY for r in regs):
        regs.append(REGISTRY)
    scratch = MetricsRegistry()
    anchor = time.time()
    for r in regs:
        scratch.merge(r, anchor=anchor)
    try:
        host = socket.gethostname()
    except OSError:
        host = "unknown"
    role = role or trace_mod.process_role()
    pid = os.getpid()
    shard = {
        "schema": SHARD_SCHEMA,
        "shard_id": _shard_id(role, pid, host),
        "anchor": anchor,
        "role": role,
        "pid": pid,
        "hostname": host,
        "metrics": scratch.dump_state(),
        **dict(extra or {}),
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp{pid}"
    with open(tmp, "w") as f:
        json.dump(shard, f)
    os.replace(tmp, path)
    return path


def load_registry_shard(path: str) -> dict:
    try:
        with open(path) as f:
            shard = json.load(f)
    except (OSError, ValueError) as e:
        raise FleetMergeError(f"{path}: unreadable registry shard ({e})") \
            from e
    if not isinstance(shard, dict) or shard.get("schema") != SHARD_SCHEMA:
        raise FleetMergeError(
            f"{path}: not a {SHARD_SCHEMA} registry shard "
            f"(schema={shard.get('schema') if isinstance(shard, dict) else None!r})"
        )
    return shard


def collect_shards(
    source,
    registry: Optional[MetricsRegistry] = None,
) -> tuple[MetricsRegistry, list[dict]]:
    """Fold registry shards into one fleet-level registry.

    ``source`` is a directory (scanned for ``registry.*.json``) or an
    explicit path list. Shards dedup by ``shard_id`` with
    latest-anchor-wins — collecting the same shard twice (or a stale copy
    next to a fresh one) changes nothing. Returns (registry, shard-meta
    rows); ``registry.to_prometheus()`` is the fleet exposition.
    """
    if isinstance(source, (str, os.PathLike)):
        paths = sorted(glob.glob(os.path.join(str(source),
                                              "registry.*.json")))
    else:
        paths = list(source)
    agg = registry if registry is not None else MetricsRegistry()
    metas = []
    for p in paths:
        shard = load_registry_shard(p)
        agg.merge(shard.get("metrics", {}), anchor=shard.get("anchor"),
                  shard_id=shard.get("shard_id") or p)
        metas.append({k: shard.get(k) for k in
                      ("shard_id", "anchor", "role", "pid", "hostname")}
                     | {"path": os.path.abspath(p)})
    metas.sort(key=lambda m: (m.get("role") or "", m.get("pid") or 0))
    return agg, metas


# --------------------------------------------------------- journal merge


def _row_time(row: Mapping) -> float:
    """Best-effort wall time of one journal row: the sub-second ``t``
    float when present (stamped since the fleet work), else the ISO
    ``time`` string parsed at second resolution, else 0."""
    t = row.get("t")
    if isinstance(t, (int, float)):
        return float(t)
    iso = row.get("time")
    if isinstance(iso, str):
        for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%M:%S"):
            try:
                return float(calendar.timegm(time.strptime(iso, fmt)))
            except ValueError:
                continue
    return 0.0


def merge_journals(paths: Sequence[str]) -> list[dict]:
    """Interleave recovery/patch journals from all processes/attempts into
    one causally ordered stream. Rows sort by wall time, then source file
    order (same-second rows from ONE process never reorder — the
    append-only file order IS their causal order); each row gains
    ``_journal`` naming its source. Unparseable lines are skipped (a torn
    tail from a crashed writer must not kill the report)."""
    rows = []
    for p in paths:
        try:
            with open(p) as f:
                lines = f.readlines()
        except OSError:
            continue
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                rows.append((_row_time(row), os.path.abspath(p), i, row))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    return [{**row, "_journal": os.path.basename(path)}
            for _, path, _, row in rows]


# ------------------------------------------------------------- discovery


@dataclasses.dataclass
class FleetRunFiles:
    """Artifact families found under one run directory."""

    run_dir: str
    traces: list
    registry_shards: list
    metrics_jsonl: list
    journals: list
    patch_journals: list
    control_ledgers: list
    bench_artifacts: list

    @property
    def empty(self) -> bool:
        return not (self.traces or self.registry_shards
                    or self.metrics_jsonl or self.journals)


def discover(run_dir: str, max_depth: int = 4) -> FleetRunFiles:
    """Scan a run directory for the telemetry convention's artifacts.

    Layout (docs/observability.md §"Fleet view"): ``--telemetry-dir``
    writes ``trace.<role>.<pid>.json`` and ``registry.<role>.<pid>.json``
    per process; driver output dirs nested under the run root contribute
    ``*metrics*.jsonl`` histories, ``recovery*.jsonl`` journals,
    ``patch-journal.jsonl``, and the control plane's
    ``control-ledger*.jsonl`` decision ledgers. Bench artifacts
    (``BENCH_DETAILS*.json`` / ``BENCH_r*.json``) join the report when
    present.
    """
    run_dir = os.path.abspath(run_dir)
    out = FleetRunFiles(run_dir=run_dir, traces=[], registry_shards=[],
                        metrics_jsonl=[], journals=[], patch_journals=[],
                        control_ledgers=[], bench_artifacts=[])
    base_depth = run_dir.rstrip(os.sep).count(os.sep)
    for root, dirs, files in os.walk(run_dir):
        if root.count(os.sep) - base_depth >= max_depth:
            dirs[:] = []
        dirs.sort()
        for name in sorted(files):
            path = os.path.join(root, name)
            if name.startswith("trace.") and name.endswith(".json"):
                out.traces.append(path)
            elif (name.endswith("-trace.json")
                  or name.endswith("_trace.json")):
                out.traces.append(path)
            elif name.startswith("registry.") and name.endswith(".json"):
                out.registry_shards.append(path)
            elif name.startswith("recovery") and name.endswith(".jsonl"):
                out.journals.append(path)
            elif name.startswith("mesh-epochs") and name.endswith(".jsonl"):
                # The elastic mesh ledger is a RecoveryJournal too: its
                # host_lost / mesh_shrunk / host_rejoined rows join the
                # merged recovery timeline and the report's Mesh section.
                out.journals.append(path)
            elif name == "patch-journal.jsonl":
                out.patch_journals.append(path)
            elif name.startswith("control-ledger") \
                    and name.endswith(".jsonl"):
                out.control_ledgers.append(path)
            elif name.endswith(".jsonl") and "metrics" in name:
                out.metrics_jsonl.append(path)
            elif name.startswith(("BENCH_DETAILS", "BENCH_r")) \
                    and name.endswith(".json"):
                out.bench_artifacts.append(path)
    return out
