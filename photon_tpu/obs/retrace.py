"""Retrace sentinel + device-memory watermark.

XLA recompilation is the serving path's silent killer: a jitted kernel that
retraces on a hot path turns a ~1 ms dispatch into a multi-second compile,
and nothing in the request path says why. The sentinel makes retraces a
first-class, *watched* metric:

* Registered kernels call :func:`note_trace` **inside their traced body**
  — the Python side-effect runs exactly once per distinct input signature,
  i.e. once per XLA compilation — bumping the process-global
  ``kernel_traces_total{kernel=...}`` counter (Prometheus-visible through
  any server's ``/metrics?format=prom``).
* After a component finishes warming its shape ladder it calls
  :func:`mark_warm`. From then on, any further trace of that kernel is a
  **retrace after warmup**: the sentinel logs a warning and emits a
  ``retrace`` instant event into the active trace, so a retrace storm shows
  up in the Perfetto timeline exactly where the latency went.

``SCORE_KERNEL_STATS`` in ``estimators.game_transformer`` is now a
back-compat alias over this module (thread-safe, resettable), and
``RowScorer.warmup`` marks the scoring kernel warm.

Also here: :func:`install_device_memory_gauges` registers callback gauges
for the accelerator's live/peak bytes (``device.memory_stats()`` where the
backend provides it — a no-op series on CPU), the watermark a capacity
planner needs next to queue depth and latency.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from photon_tpu.obs import trace as _trace
from photon_tpu.obs.metrics import REGISTRY, MetricsRegistry

__all__ = [
    "RE_SOLVER_KERNELS",
    "compile_watch",
    "expected_compiles",
    "note_trace",
    "mark_warm",
    "clear_warm",
    "traces",
    "retraces_after_warmup",
    "all_traces",
    "reset",
    "install_device_memory_gauges",
]

# The registered random-effect bucket-solver kernels (game/newton_re.py +
# game/random_effect.py). One place so the compile/solve timing split, the
# descent-loop warmup marking, and the tests all watch the same names.
RE_SOLVER_KERNELS = (
    "fit_bucket_newton",
    "fit_bucket_newton_dual",
    "fit_bucket_vmapped",
)

logger = logging.getLogger("photon_tpu.obs")

_lock = threading.Lock()
_warm: set[str] = set()
_tls = threading.local()

_TRACES = REGISTRY.counter(
    "kernel_traces_total",
    "XLA compilations per registered jitted kernel (traced-body count)",
)
_RETRACES = REGISTRY.counter(
    "kernel_retraces_after_warmup_total",
    "Compilations that happened AFTER the kernel was marked warm — each one "
    "stalled a hot path behind XLA",
)


class expected_compiles:
    """``with expected_compiles():`` — this THREAD's compilations are
    deliberate (a hot-swap warming a new version's shape ladder) and must
    not fire retrace warnings. Thread-local on purpose: while one thread
    warms a swap, retraces on the still-serving threads keep warning —
    disarming the sentinel process-wide would blind it during exactly the
    window a swap-induced retrace storm would start. Compile COUNTS still
    accrue; only the after-warmup warning/event/counter are skipped."""

    __slots__ = ()

    def __enter__(self) -> None:
        _tls.expected = getattr(_tls, "expected", 0) + 1

    def __exit__(self, *exc) -> None:
        _tls.expected -= 1


class compile_watch:
    """``with compile_watch() as cw: out = jitted(...)`` — split first-trace
    compile time from solve time via the sentinel's trace counters.

    Wrap the UNSYNCED dispatch only: jit tracing + XLA compilation run
    synchronously in the calling thread before dispatch returns, while
    execution is enqueued asynchronously — so when ``cw.compiled`` is
    non-empty the dispatch wall time is (to enqueue overhead, microseconds)
    the compile time, and when it is empty the wall time is pure dispatch.
    This is how ``train_random_effects`` stamps ``compile_seconds`` into
    ``LAST_BUCKET_TIMINGS`` / bench artifacts / trace spans WITHOUT the two
    blocking device syncs per bucket that full timing mode needs.

    ``cw.seconds`` — dispatch wall. ``cw.compiled`` — {kernel: new traces}
    for watched kernels that compiled inside the block. ``cw.compile_seconds``
    — ``seconds`` if anything compiled, else 0.0.
    """

    def __init__(self, kernels=RE_SOLVER_KERNELS) -> None:
        self.kernels = tuple(kernels)
        self.seconds = 0.0
        self.compiled: dict = {}

    def __enter__(self) -> "compile_watch":
        import time as _time

        self._before = {k: int(_TRACES.value(kernel=k)) for k in self.kernels}
        self._t0 = _time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        import time as _time

        self.seconds = _time.perf_counter() - self._t0
        self.compiled = {
            k: int(_TRACES.value(kernel=k)) - b
            for k, b in self._before.items()
            if int(_TRACES.value(kernel=k)) > b
        }

    @property
    def compile_seconds(self) -> float:
        return self.seconds if self.compiled else 0.0


def note_trace(kernel: str) -> None:
    """Record one compilation of ``kernel``. Call from inside the jitted
    function body (runs only at trace time, costs nothing per dispatch)."""
    _TRACES.inc(kernel=kernel)
    if getattr(_tls, "expected", 0):
        return
    with _lock:
        warmed = kernel in _warm
    if warmed:
        _RETRACES.inc(kernel=kernel)
        logger.warning(
            "kernel %s retraced after warmup (trace #%d) — a hot-path "
            "request is paying an XLA compile; check for unstable shapes "
            "or dtypes", kernel, int(_TRACES.value(kernel=kernel)),
        )
        _trace.instant(
            "retrace", cat="warning",
            kernel=kernel, traces=int(_TRACES.value(kernel=kernel)),
        )


def mark_warm(kernel: str) -> None:
    """Declare ``kernel``'s shape ladder fully compiled; later traces warn."""
    with _lock:
        _warm.add(kernel)


def clear_warm(kernel: Optional[str] = None) -> None:
    """Forget warm state (model swap re-warms; tests)."""
    with _lock:
        if kernel is None:
            _warm.clear()
        else:
            _warm.discard(kernel)


def traces(kernel: str) -> int:
    return int(_TRACES.value(kernel=kernel))


def retraces_after_warmup(kernel: str) -> int:
    return int(_RETRACES.value(kernel=kernel))


def all_traces() -> dict:
    """kernel → compilation count, for JSON snapshots."""
    return {
        labels.get("kernel", ""): int(v)
        for labels, v in _TRACES.collect()
        if labels
    }


def reset() -> None:
    """Zero counters and warm state (tests)."""
    _TRACES.reset()
    _RETRACES.reset()
    clear_warm()


def _memory_stats() -> dict:
    """{(label_tuple): bytes} series for live + peak device memory, or {}
    when the backend exposes no stats (CPU)."""
    try:
        import jax

        out = {}
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            key = f"{d.platform}:{d.id}"
            if "bytes_in_use" in stats:
                out[(("device", key), ("kind", "in_use"))] = float(
                    stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                out[(("device", key), ("kind", "peak"))] = float(
                    stats["peak_bytes_in_use"])
        return out
    except Exception:  # noqa: BLE001 - a sick backend must not break /metrics
        return {}


def install_device_memory_gauges(
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Register the ``device_memory_bytes`` callback gauge (live + peak
    watermark per device). Idempotent; callers pass their own registry or
    default to the process-global one."""
    (registry or REGISTRY).gauge_fn(
        "device_memory_bytes",
        _memory_stats,
        "Device memory watermark: bytes_in_use and peak_bytes_in_use per "
        "local device (absent on backends without memory_stats)",
    )
