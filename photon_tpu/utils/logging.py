"""Logging, stage timing, and structured metrics.

Parity: reference ⟦photon-api/.../util/PhotonLogger.scala, Timed.scala⟧
(SURVEY.md §5.1/§5.5): a logger that writes a log file into the job's output
directory alongside stderr, a ``Timed`` block that logs wall-clock per driver
stage, and — richer than the reference, per SURVEY's rebuild note — structured
JSONL metrics for machine consumption.
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Iterable, Mapping, Optional

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


class PhotonLogger:
    """Logger bound to an output directory: ``<dir>/photon.log`` + stderr.

    Use as a context manager so file handlers are released deterministically
    (the reference closes its HDFS log stream at driver exit).
    """

    def __init__(
        self,
        output_dir: Optional[str] = None,
        name: str = "photon_tpu",
        level: int = logging.INFO,
    ):
        self.logger = logging.getLogger(name)
        self.logger.setLevel(level)
        self._handlers: list[logging.Handler] = []

        have_stream = any(
            isinstance(h, logging.StreamHandler)
            and not isinstance(h, logging.FileHandler)
            for h in self.logger.handlers
        )
        if not have_stream:
            sh = logging.StreamHandler()
            sh.setFormatter(logging.Formatter(_FORMAT))
            self.logger.addHandler(sh)
            self._handlers.append(sh)
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            fh = logging.FileHandler(os.path.join(output_dir, "photon.log"))
            fh.setFormatter(logging.Formatter(_FORMAT))
            self.logger.addHandler(fh)
            self._handlers.append(fh)

    def __enter__(self) -> logging.Logger:
        return self.logger

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        for h in self._handlers:
            self.logger.removeHandler(h)
            h.close()
        self._handlers.clear()


class Timed:
    """``with Timed("read data", logger): ...`` — logs elapsed wall-clock,
    and records it in ``Timed.last_seconds`` for programmatic use."""

    def __init__(self, stage: str, logger: Optional[logging.Logger] = None):
        self.stage = stage
        self.logger = logger or logging.getLogger("photon_tpu")
        self.seconds: float = 0.0

    def __enter__(self) -> "Timed":
        self._t0 = time.perf_counter()
        self.logger.info("%s: started", self.stage)
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        status = "failed" if exc_type else "done"
        self.logger.info("%s: %s in %.3fs", self.stage, status, self.seconds)


# Rotation defaults for write_metrics_jsonl (overridable per call or via
# env): a long-run serve bench flushing every minute must not fill the
# disk, so growth is bounded at max_bytes x (max_rotated + 1) per path.
DEFAULT_METRICS_MAX_BYTES = 64 << 20
DEFAULT_METRICS_MAX_ROTATED = 3


def _rotate_metrics_file(path: str, max_bytes: int, max_rotated: int) -> None:
    """Size-gated rotation: ``path`` → ``path.1`` → ... → ``path.N``.

    Serialized across processes by an flock on ``path.rotate.lock`` (the
    size is re-checked under the lock, so the losing racer sees the fresh
    file and does nothing). A writer that already holds an O_APPEND
    descriptor to the renamed file keeps appending to ``path.1`` — whole
    lines, still atomic — and its next call lands on the fresh file.
    """
    try:
        if os.path.getsize(path) < max_bytes:
            return
    except OSError:
        return  # nothing to rotate
    try:
        import fcntl

        lock = open(path + ".rotate.lock", "a")
    except (ImportError, OSError):
        lock = None
    try:
        if lock is not None:
            try:
                fcntl.flock(lock, fcntl.LOCK_EX)
            except OSError:
                pass
        try:
            if os.path.getsize(path) < max_bytes:
                return  # another writer rotated while we waited
        except OSError:
            return
        try:
            if max_rotated <= 0:
                os.remove(path)
                return
            for i in range(max_rotated - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            os.replace(path, f"{path}.1")
        except OSError:
            pass  # rotation is best-effort; the append below still works
    finally:
        if lock is not None:
            lock.close()


def write_metrics_jsonl(
    path: str,
    records: Iterable[Mapping[str, Any]],
    max_bytes: int = None,
    max_rotated: int = None,
) -> None:
    """Append metric records as JSON lines (one object per line).

    Append-only contract: each record is serialized fully on the host and
    written as ONE unbuffered ``write()`` of a complete ``...\\n`` line onto
    an ``O_APPEND`` descriptor. The kernel applies each append atomically at
    the current end-of-file, so a concurrent writer (another process
    flushing to the same metrics file, a supervisor restart racing the old
    process's final flush) interleaves whole lines, never torn ones — and a
    crash mid-flush can lose at most the not-yet-written records, never
    corrupt previously-written lines. Readers may therefore tail the file
    while it grows and treat every complete line as a valid JSON object.

    Growth is bounded: once the file reaches ``max_bytes`` (default 64 MB;
    env ``PHOTON_METRICS_MAX_BYTES``, 0 disables) it rotates to ``path.1``
    .. ``path.N`` (``max_rotated``, default 3; env
    ``PHOTON_METRICS_MAX_ROTATED``) BEFORE this call's appends, so every
    line within one call lands in one file and rotation never tears a
    record — the whole-line contract above holds across rotations.
    """
    # Malformed env values fall back to the defaults: a typo'd override
    # must degrade rotation, never kill the periodic metrics thread that
    # calls this on every flush.
    if max_bytes is None:
        try:
            max_bytes = int(os.environ.get(
                "PHOTON_METRICS_MAX_BYTES", DEFAULT_METRICS_MAX_BYTES))
        except (TypeError, ValueError):
            max_bytes = DEFAULT_METRICS_MAX_BYTES
    if max_rotated is None:
        try:
            max_rotated = int(os.environ.get(
                "PHOTON_METRICS_MAX_ROTATED", DEFAULT_METRICS_MAX_ROTATED))
        except (TypeError, ValueError):
            max_rotated = DEFAULT_METRICS_MAX_ROTATED
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if max_bytes > 0:
        _rotate_metrics_file(path, max_bytes, max_rotated)
    with open(path, "ab", buffering=0) as f:
        for rec in records:
            f.write((json.dumps(dict(rec)) + "\n").encode("utf-8"))


class LatencyHistogram:
    """Log-spaced latency histogram with approximate quantiles.

    Serving instrumentation (docs/serving.md): memory stays bounded under
    any traffic volume (fixed bin array, no sample retention) while
    p50/p95/p99 stay within one bin's relative width (~12% at the default
    20 bins/decade). Sum and max are tracked exactly. Thread-safe.
    """

    def __init__(
        self,
        lo_ms: float = 0.05,
        hi_ms: float = 60_000.0,
        bins_per_decade: int = 20,
    ):
        self._lo = lo_ms / 1e3
        self._bins_per_decade = int(bins_per_decade)
        self._ratio = 10.0 ** (1.0 / bins_per_decade)
        self._log_ratio = math.log(self._ratio)
        n = int(math.ceil(math.log(hi_ms / lo_ms) / self._log_ratio)) + 1
        self._counts = [0] * (n + 2)  # + underflow/overflow bins
        self._lock = threading.Lock()
        self._sum = 0.0
        self._max = 0.0
        self._n = 0

    def observe(self, seconds: float) -> None:
        if seconds <= 0:
            seconds = 1e-9
        b = int(math.floor(math.log(seconds / self._lo) / self._log_ratio)) + 1
        b = min(max(b, 0), len(self._counts) - 1)
        with self._lock:
            self._counts[b] += 1
            self._sum += seconds
            self._max = max(self._max, seconds)
            self._n += 1

    def quantile_ms(self, q: float) -> float:
        """Approximate q-quantile in milliseconds (geometric bin midpoint)."""
        with self._lock:
            n = self._n
            counts = list(self._counts)
        if n == 0:
            return 0.0
        target = q * n
        seen = 0
        for b, c in enumerate(counts):
            seen += c
            if seen >= target:
                if b == 0:
                    return self._lo * 1e3
                lo = self._lo * self._ratio ** (b - 1)
                return lo * (self._ratio ** 0.5) * 1e3
        return self._max * 1e3

    def snapshot(self) -> dict:
        with self._lock:
            n, s, mx = self._n, self._sum, self._max
        return {
            "count": n,
            "mean_ms": round(s / n * 1e3, 3) if n else 0.0,
            "p50_ms": round(self.quantile_ms(0.50), 3),
            "p95_ms": round(self.quantile_ms(0.95), 3),
            "p99_ms": round(self.quantile_ms(0.99), 3),
            "max_ms": round(mx * 1e3, 3),
        }

    # -------------------------------------------------- fleet aggregation
    #
    # Full mergeable state (not just the quantile snapshot): per-process
    # registry shards dump it, the fleet aggregator adds bin counts
    # elementwise — exact, associative, commutative (obs/fleet.py).

    def state(self) -> dict:
        with self._lock:
            return {
                "lo_ms": self._lo * 1e3,
                "bins_per_decade": self._bins_per_decade,
                "counts": list(self._counts),
                "sum": self._sum,
                "max": self._max,
                "n": self._n,
            }

    @classmethod
    def from_state(cls, state: Mapping[str, Any]) -> "LatencyHistogram":
        """Reconstruct a histogram with EXACTLY the state's bin layout —
        the aggregator's entry point for a shard whose exporter used a
        non-default layout (bin count is restored verbatim, not re-derived
        from a hi_ms round-trip)."""
        h = cls(lo_ms=float(state["lo_ms"]),
                bins_per_decade=int(state.get("bins_per_decade", 20)))
        with h._lock:
            h._counts = [int(c) for c in state["counts"]]
            h._sum = float(state["sum"])
            h._max = float(state["max"])
            h._n = int(state["n"])
        return h

    def merge_state(self, state: Mapping[str, Any]) -> None:
        """Fold another histogram's :meth:`state` into this one. Refuses a
        mismatched bin layout — summing misaligned bins would silently
        corrupt every quantile downstream."""
        counts = state["counts"]
        if (len(counts) != len(self._counts)
                or abs(float(state["lo_ms"]) - self._lo * 1e3) > 1e-9
                or int(state.get("bins_per_decade",
                                 self._bins_per_decade))
                != self._bins_per_decade):
            raise ValueError(
                "histogram bin layout mismatch: cannot merge "
                f"{len(counts)} bins @ lo={state['lo_ms']}ms into "
                f"{len(self._counts)} bins @ lo={self._lo * 1e3}ms"
            )
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += int(c)
            self._sum += float(state["sum"])
            self._max = max(self._max, float(state["max"]))
            self._n += int(state["n"])
