"""Logging, stage timing, and structured metrics.

Parity: reference ⟦photon-api/.../util/PhotonLogger.scala, Timed.scala⟧
(SURVEY.md §5.1/§5.5): a logger that writes a log file into the job's output
directory alongside stderr, a ``Timed`` block that logs wall-clock per driver
stage, and — richer than the reference, per SURVEY's rebuild note — structured
JSONL metrics for machine consumption.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Iterable, Mapping, Optional

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


class PhotonLogger:
    """Logger bound to an output directory: ``<dir>/photon.log`` + stderr.

    Use as a context manager so file handlers are released deterministically
    (the reference closes its HDFS log stream at driver exit).
    """

    def __init__(
        self,
        output_dir: Optional[str] = None,
        name: str = "photon_tpu",
        level: int = logging.INFO,
    ):
        self.logger = logging.getLogger(name)
        self.logger.setLevel(level)
        self._handlers: list[logging.Handler] = []

        have_stream = any(
            isinstance(h, logging.StreamHandler)
            and not isinstance(h, logging.FileHandler)
            for h in self.logger.handlers
        )
        if not have_stream:
            sh = logging.StreamHandler()
            sh.setFormatter(logging.Formatter(_FORMAT))
            self.logger.addHandler(sh)
            self._handlers.append(sh)
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            fh = logging.FileHandler(os.path.join(output_dir, "photon.log"))
            fh.setFormatter(logging.Formatter(_FORMAT))
            self.logger.addHandler(fh)
            self._handlers.append(fh)

    def __enter__(self) -> logging.Logger:
        return self.logger

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        for h in self._handlers:
            self.logger.removeHandler(h)
            h.close()
        self._handlers.clear()


class Timed:
    """``with Timed("read data", logger): ...`` — logs elapsed wall-clock,
    and records it in ``Timed.last_seconds`` for programmatic use."""

    def __init__(self, stage: str, logger: Optional[logging.Logger] = None):
        self.stage = stage
        self.logger = logger or logging.getLogger("photon_tpu")
        self.seconds: float = 0.0

    def __enter__(self) -> "Timed":
        self._t0 = time.perf_counter()
        self.logger.info("%s: started", self.stage)
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self.seconds = time.perf_counter() - self._t0
        status = "failed" if exc_type else "done"
        self.logger.info("%s: %s in %.3fs", self.stage, status, self.seconds)


def write_metrics_jsonl(
    path: str, records: Iterable[Mapping[str, Any]]
) -> None:
    """Append metric records as JSON lines (one object per line)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        for rec in records:
            f.write(json.dumps(dict(rec)) + "\n")
