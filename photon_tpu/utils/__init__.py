"""Cross-cutting utilities — reference ⟦photon-api/.../util⟧ (SURVEY.md §5)."""
from photon_tpu.utils.logging import (
    LatencyHistogram,
    PhotonLogger,
    Timed,
    write_metrics_jsonl,
)
from photon_tpu.utils.vectors import (
    DoubleRange,
    active_indices,
    all_finite,
    csr_to_ell,
    dense_to_ell,
    ell_to_csr,
    ell_to_dense,
    is_almost_zero,
)

__all__ = [
    "LatencyHistogram", "PhotonLogger", "Timed", "write_metrics_jsonl",
    "DoubleRange", "active_indices", "all_finite", "csr_to_ell",
    "dense_to_ell", "ell_to_csr", "ell_to_dense", "is_almost_zero",
]
