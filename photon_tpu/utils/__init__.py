"""Cross-cutting utilities — reference ⟦photon-api/.../util⟧ (SURVEY.md §5)."""
from photon_tpu.utils.logging import PhotonLogger, Timed, write_metrics_jsonl

__all__ = ["PhotonLogger", "Timed", "write_metrics_jsonl"]
