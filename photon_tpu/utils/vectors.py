"""Vector/matrix representation conversions and numeric guards.

Parity: reference ⟦photon-lib/.../util/VectorUtils.scala⟧ /
⟦MathUtils.scala⟧ / ⟦DoubleRange.scala⟧ (SURVEY.md §2.1 Math/util):
conversions between sparse and dense vector forms, active-index iteration,
and the numeric tolerance helpers shared by optimizers and validators.

TPU-first shapes: the interchange formats are the padded-ELL arrays of
``SparseFeatures`` (``idx[N, K]`` / ``val[N, K]``, ghost column == ``dim``)
and CSR triples — both static-shape-friendly — rather than per-row pointer
objects. Everything here is host-side NumPy (construction-time utilities;
the device hot path lives in ``ops/``).
"""
from __future__ import annotations

import dataclasses
from typing import Iterator, Sequence

import numpy as np

__all__ = [
    "EPSILON",
    "is_almost_zero",
    "all_finite",
    "DoubleRange",
    "ell_to_dense",
    "dense_to_ell",
    "ell_to_csr",
    "csr_to_ell",
    "active_indices",
    "iter_active",
]

# The "numerically zero" tolerance for HOST-side double-precision logic
# (config comparisons, convergence bookkeeping — the reference's MathUtils
# epsilon role; Breeze is f64). It is far below f32 machine-eps on purpose:
# device-side f32 round-off tolerances are per-test/per-check, not a global.
EPSILON = 1e-12


def is_almost_zero(x: float, eps: float = EPSILON) -> bool:
    return abs(float(x)) < eps


def all_finite(a) -> bool:
    """True iff every element is finite (the validators' inner check)."""
    return bool(np.isfinite(np.asarray(a)).all())


@dataclasses.dataclass(frozen=True)
class DoubleRange:
    """Closed numeric range with validation — the reference's hyperparameter
    /config range type (⟦DoubleRange.scala⟧)."""

    start: float
    end: float

    def __post_init__(self):
        if not (np.isfinite(self.start) and np.isfinite(self.end)):
            raise ValueError(f"range bounds must be finite: {self}")
        if self.start > self.end:
            raise ValueError(f"range start > end: {self}")

    def __contains__(self, x: float) -> bool:
        return self.start <= x <= self.end

    def clamp(self, x: float) -> float:
        return min(max(x, self.start), self.end)

    def transform(self, fn) -> "DoubleRange":
        """Monotone transform of both bounds (e.g. log10 for reg-weight
        search spaces); a decreasing ``fn`` (e.g. 1/x) swaps them so the
        result is still a valid range."""
        a, b = float(fn(self.start)), float(fn(self.end))
        return DoubleRange(min(a, b), max(a, b))


# ---------------------------------------------------------------------------
# ELL <-> dense <-> CSR


def ell_to_dense(idx: np.ndarray, val: np.ndarray, dim: int) -> np.ndarray:
    """Padded-ELL arrays -> dense ``[N, dim]`` (duplicates accumulate,
    ghost entries drop). Small-data/debug utility."""
    idx = np.asarray(idx)
    val = np.asarray(val)
    n, k = idx.shape
    out = np.zeros((n, dim), dtype=val.dtype)
    rows = np.repeat(np.arange(n), k)
    flat_i, flat_v = idx.ravel(), val.ravel()
    keep = flat_i < dim
    np.add.at(out, (rows[keep], flat_i[keep]), flat_v[keep])
    return out


def _pack_ell(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n: int,
    dim: int,
    counts: np.ndarray,
    max_nnz: int | None,
) -> tuple[np.ndarray, np.ndarray]:
    """Shared slot packer: row-major-sorted COO entries -> padded ELL.

    (Same rank-within-row trick as ``io/streaming.py ell_from_triples`` /
    ``data/batch.py ell_from_rows`` — those serve different contracts:
    device-array SparseFeatures with intercept insertion, and per-row Python
    lists. This is the host-NumPy interchange form.)"""
    k = int(counts.max(initial=0)) if max_nnz is None else max_nnz
    k = max(k, 1)
    if counts.max(initial=0) > k:
        raise ValueError(
            f"row has {int(counts.max(initial=0))} nonzeros > max_nnz={k}"
        )
    idx = np.full((n, k), dim, dtype=np.int32)
    val = np.zeros((n, k), dtype=np.asarray(vals).dtype)
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    slot = np.arange(len(rows)) - starts[:-1][rows]
    idx[rows, slot] = cols
    val[rows, slot] = vals
    return idx, val


def dense_to_ell(
    x: np.ndarray, max_nnz: int | None = None, tol: float = 0.0
) -> tuple[np.ndarray, np.ndarray, int]:
    """Dense ``[N, D]`` -> padded ELL ``(idx, val, dim)``; entries with
    ``|x| <= tol`` are treated as structural zeros. K = max row nnz (or
    ``max_nnz``; raises if any row exceeds it — silent truncation would
    corrupt features)."""
    x = np.asarray(x)
    n, d = x.shape
    mask = np.abs(x) > tol
    rows, cols = np.nonzero(mask)  # row-major sorted
    idx, val = _pack_ell(
        rows, cols, x[rows, cols], n, d, mask.sum(axis=1), max_nnz
    )
    return idx, val, d


def ell_to_csr(
    idx: np.ndarray, val: np.ndarray, dim: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Padded ELL -> CSR ``(indptr[N+1], indices, values)`` with ghost
    entries dropped (the interchange format for scipy/host tooling)."""
    idx = np.asarray(idx)
    val = np.asarray(val)
    n, k = idx.shape
    keep = idx < dim
    counts = keep.sum(axis=1)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return indptr, idx[keep].astype(np.int32), val[keep]


def csr_to_ell(
    indptr: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    dim: int,
    max_nnz: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """CSR -> padded ELL ``(idx, val)`` (K = max row nnz or ``max_nnz``)."""
    indptr = np.asarray(indptr)
    n = len(indptr) - 1
    counts = np.diff(indptr)
    rows = np.repeat(np.arange(n), counts)
    return _pack_ell(
        rows, np.asarray(indices), np.asarray(values), n, dim, counts, max_nnz
    )


def active_indices(idx: np.ndarray, dim: int) -> np.ndarray:
    """Sorted unique feature ids present in the data (the reference's
    active-index iteration; feeds subspace projection)."""
    flat = np.asarray(idx).ravel()
    return np.unique(flat[flat < dim]).astype(np.int32)


def iter_active(
    idx_row: Sequence[int], val_row: Sequence[float], dim: int
) -> Iterator[tuple[int, float]]:
    """Iterate one ELL row's real ``(index, value)`` pairs, skipping ghost
    padding — per-row debug/export convenience."""
    for i, v in zip(idx_row, val_row):
        if i < dim:
            yield int(i), float(v)
