"""Streaming sharded Avro ingest: block-level decode, columnar assembly.

Parity: the reference reads training data through spark-avro — a cluster of
executors each decoding its own file splits into per-shard feature vectors
(SURVEY.md §2.3 ``AvroDataReader``, §2.6 "host-side pre-sharding of input
files … sharded input pipeline instead of shuffle"). The round-1/round-2
rebuild decoded records one at a time into per-row Python lists, which walled
off every at-scale config (VERDICT round-2 "What's missing" #1).

This module is the scale path:

* the Avro *container framing* (block headers, sync markers, deflate) is
  handled here, in Python — cheap, per-block;
* each block payload goes to the native decoder
  (``photon_tpu/native/avro_block.cc``) as one ctypes call: records are
  parsed by a compiled schema program straight into columnar buffers —
  numeric columns, dictionary-encoded string columns, and per-feature-shard
  ``(row, col, value)`` triples looked up through a MurmurHash64A
  open-addressing table built from the shard's ``IndexMap``;
* every ``chunk_rows`` rows the buffers are snapshotted into a
  :class:`GameDataChunk`: NumPy columns plus padded-ELL feature arrays
  assembled by vectorized scatter (no per-row Python objects anywhere);
* :meth:`StreamingAvroReader.read` concatenates chunks into the same
  ``GameDataBundle`` the per-record reader produces — bit-identical label /
  offset / weight / feature semantics (tested against it) — while
  :meth:`StreamingAvroReader.iter_chunks` streams with host memory constant
  in ``chunk_rows`` (caveat: the uid dictionary grows with *unique* uids;
  pass ``capture_uids=False`` on billion-row training flows that never read
  them — entity-tag dictionaries only grow with unique entities), and
  :meth:`GameDataChunk.split` gives per-device host pre-sharding for the
  data-parallel feed.

Schemas the compiler cannot express (non-record top level, feature bags that
are not arrays of (name, term?, value) records) raise :class:`Unsupported`;
``AvroDataReader.read`` catches it and falls back to the per-record path, so
the streaming engine is a transparent accelerator, not a new dialect.
"""
from __future__ import annotations

import ctypes
import dataclasses
import logging
import os
import time
from typing import Callable, Iterator, Mapping, Optional, Sequence

import numpy as np

from photon_tpu.faults import fault_point
from photon_tpu.obs import trace_span

from photon_tpu.data.batch import SparseFeatures
from photon_tpu.index.index_map import (
    INTERCEPT_NAME,
    INTERCEPT_TERM,
    IndexMap,
    feature_key,
)
from photon_tpu.io import avro
from photon_tpu.io.avro import SchemaError
from photon_tpu import native

logger = logging.getLogger("photon_tpu.io")

# Type-tree node kinds — must match avro_block.cc.
K_NULL, K_BOOL, K_INT, K_LONG, K_FLOAT, K_DOUBLE = 0, 1, 2, 3, 4, 5
K_BYTES, K_STRING, K_FIXED, K_ENUM, K_ARRAY, K_MAP = 6, 7, 8, 9, 10, 11
K_RECORD, K_UNION = 12, 13

OP_SKIP, OP_NUM, OP_STR, OP_BAG, OP_META = 0, 1, 2, 3, 4

_PRIM_KINDS = {
    "null": K_NULL, "boolean": K_BOOL, "int": K_INT, "long": K_LONG,
    "float": K_FLOAT, "double": K_DOUBLE, "bytes": K_BYTES, "string": K_STRING,
}

_ERRORS = {
    -1: "truncated block payload",
    -2: "malformed varint",
    -3: "union branch out of range",
    -4: "unexpected type in data",
    -5: "missing id tag",
    -6: "nesting too deep",
    -7: "native allocation failed (host out of memory?)",
}


class Unsupported(Exception):
    """Schema/config shape the streaming compiler cannot express; callers
    fall back to the per-record Python reader."""


# ---------------------------------------------------------------------------
# schema -> type tree + program


def _build_ttree(schema, names: dict, out: list, depth: int = 0) -> int:
    """Flatten a (resolved) schema into the pre-order int32 type tree;
    returns the node offset."""
    if depth > 32:
        raise Unsupported("schema nesting too deep")
    schema = avro._resolve(schema, names)
    if isinstance(schema, list):  # union
        off = len(out)
        out.extend([K_UNION, len(schema)])
        slots = len(out)
        out.extend([0] * len(schema))
        for i, br in enumerate(schema):
            out[slots + i] = _build_ttree(br, names, out, depth + 1)
        return off
    t = schema if isinstance(schema, str) else schema["type"]
    if t in _PRIM_KINDS:
        off = len(out)
        out.append(_PRIM_KINDS[t])
        return off
    if t == "fixed":
        off = len(out)
        out.extend([K_FIXED, int(schema["size"])])
        return off
    if t == "enum":
        off = len(out)
        out.append(K_ENUM)
        return off
    if t in ("array", "map"):
        off = len(out)
        out.extend([K_ARRAY if t == "array" else K_MAP, 0])
        child_key = "items" if t == "array" else "values"
        out[off + 1] = _build_ttree(schema[child_key], names, out, depth + 1)
        return off
    if t == "record":
        fields = schema.get("fields", ())
        off = len(out)
        out.extend([K_RECORD, len(fields)])
        slots = len(out)
        out.extend([0] * len(fields))
        for i, f in enumerate(fields):
            out[slots + i] = _build_ttree(f["type"], names, out, depth + 1)
        return off
    raise Unsupported(f"unsupported avro type {t!r}")


def _static_branches(schema, names: dict):
    """Yield the concrete (non-union) branches of a possibly-union schema."""
    schema = avro._resolve(schema, names)
    if isinstance(schema, list):
        for br in schema:
            yield from _static_branches(br, names)
    else:
        yield schema


def _find_bag_record(field_schema, names: dict):
    """For a feature-bag field: the array-of-record branch's record schema."""
    recs = []
    for br in _static_branches(field_schema, names):
        t = br if isinstance(br, str) else br["type"]
        if t == "array":
            item = avro._resolve(br["items"], names)
            it = item if isinstance(item, str) else item.get("type")
            if it == "record":
                recs.append(item)
    if len(recs) != 1:
        raise Unsupported("feature bag is not a unique array-of-record field")
    return recs[0]


def _is_fast_bag(rec, names: dict) -> bool:
    """True for the exact reference NameTermValueAvro layout —
    [name: string, term: [null, string], value: double] — which the native
    decoder parses with a straight-line fast path."""
    fields = rec.get("fields", ())
    if len(fields) != 3:
        return False
    if [f["name"] for f in fields] != ["name", "term", "value"]:
        return False
    def prim(s):
        s = avro._resolve(s, names)
        return s.get("type") if isinstance(s, dict) else s

    t_t = avro._resolve(fields[1]["type"], names)
    if prim(fields[0]["type"]) != "string" or prim(fields[2]["type"]) != "double":
        return False
    if not (isinstance(t_t, list) and len(t_t) == 2):
        return False
    return prim(t_t[0]) == "null" and prim(t_t[1]) == "string"


def _is_map_like(field_schema, names: dict) -> bool:
    return any(
        (br if isinstance(br, str) else br["type"]) == "map"
        for br in _static_branches(field_schema, names)
    )


@dataclasses.dataclass
class Program:
    """Compiled decode program + column layout for one (schema, config)."""

    ttree: np.ndarray          # int32
    ops: np.ndarray            # int32, flattened
    op_starts: np.ndarray      # int64
    num_names: list            # numeric column names (response/offset/...)
    null_defaults: np.ndarray  # float64 per numeric column
    str_names: list            # string column names (uid + tags)
    tag_names: list            # names referenced by OP_META
    shard_order: list          # shard ids in table order
    tables: list               # (hashes u64[2^k], vals int32[2^k]) per shard
    n_label_cols: int          # response + aliases occupy num cols [0, n)


def _hash_keys(keys: list[bytes]) -> np.ndarray:
    """Key hashes via the native ``hash64`` (MurmurHash64A) — the SAME
    function the decoder applies to decoded feature keys, so the table and
    the probe always agree. Requires the native library (without it the
    streaming engine is unavailable anyway)."""
    lib = native.get_lib()
    if lib is None:
        raise Unsupported("native decoder unavailable")
    blob = b"".join(keys)
    offs = np.zeros(len(keys) + 1, np.int64)
    np.cumsum([len(k) for k in keys], out=offs[1:])
    out = np.zeros(len(keys), np.uint64)
    if keys:
        arr = np.frombuffer(blob, np.uint8) if blob else np.zeros(1, np.uint8)
        lib.ph_hash_keys(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(keys),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
    return out


def _build_table(index_map: IndexMap) -> tuple[np.ndarray, np.ndarray]:
    """Open-addressing (hash, value) arrays for one shard's feature index.

    64-bit MurmurHash64A over the full ``name\\x01term`` key; distinct keys
    colliding on the full 64-bit hash (probability ~n²/2⁶⁵) are detected and
    rejected — the caller falls back to the exact-string reader rather than
    silently merging features.
    """
    try:
        keys = [k.encode("utf-8") for k in index_map.keys_in_order]
    except AttributeError:
        # Mmap-backed maps: reconstruct keys through the reverse blob.
        keys = [
            feature_key(*index_map.get_feature(i)).encode("utf-8")
            for i in range(len(index_map))
        ]
    hashes = _hash_keys(keys)
    if len(np.unique(hashes)) != len(hashes):
        raise Unsupported("64-bit feature-key hash collision")
    size = 1
    while size < 2 * max(len(keys), 1):
        size *= 2
    t_hash = np.zeros(size, np.uint64)
    t_val = np.full(size, -1, np.int32)
    mask = size - 1
    # Vectorized first placement: the first key hashing to each slot lands
    # without probing; only slot-colliding keys take the Python probe loop.
    home = (hashes & np.uint64(mask)).astype(np.int64)
    order = np.argsort(home, kind="stable")
    first = np.ones(len(keys), bool)
    first[order[1:]] = home[order[1:]] != home[order[:-1]]
    t_hash[home[first]] = hashes[first]
    t_val[home[first]] = np.flatnonzero(first).astype(np.int32)
    for i in np.flatnonzero(~first):
        j = int(home[i])
        while t_hash[j] != 0:
            j = (j + 1) & mask
        t_hash[j] = hashes[i]
        t_val[j] = i
    return t_hash, t_val


def compile_program(
    schema,
    columns,
    shard_configs: Mapping[str, object],
    index_maps: Mapping[str, IndexMap],
    id_tag_columns: Sequence[str],
    capture_uids: bool = True,
) -> Program:
    """Compile (writer schema, reader config) into a native decode program."""
    schema = avro.parse_schema(schema)
    names: dict = {}
    avro._collect_names(schema, names)
    top = avro._resolve(schema, names)
    if not isinstance(top, dict) or top.get("type") != "record":
        raise Unsupported("top-level schema is not a record")

    # Column layout.
    from photon_tpu.io.data_reader import response_columns

    response_cols = list(response_columns(columns))
    field_names = [f["name"] for f in top["fields"]]
    present_resp = [c for c in response_cols if c in field_names]
    num_names = (present_resp or [columns.response]) + [
        columns.offset, columns.weight
    ]
    n_label = max(len(present_resp), 1)
    null_defaults = np.array([np.nan] * n_label + [0.0, 1.0], np.float64)
    str_names = ["__uid__"] + list(id_tag_columns)
    tag_names = list(id_tag_columns)

    # bag -> shards feeding from it.
    bag_shards: dict[str, list[int]] = {}
    shard_order = list(index_maps)
    for si, shard in enumerate(shard_order):
        for bag in shard_configs[shard].feature_bags:
            bag_shards.setdefault(bag, []).append(si)

    ttree: list[int] = []
    ops: list[int] = []
    op_starts: list[int] = []

    def emit(*vals):
        op_starts.append(len(ops))
        ops.extend(int(v) for v in vals)

    for fpos, f in enumerate(top["fields"]):
        name = f["name"]
        toff = _build_ttree(f["type"], names, ttree)
        if name in present_resp:
            emit(OP_NUM, toff, present_resp.index(name), 1)
        elif name == columns.offset:
            emit(OP_NUM, toff, n_label, 1)
        elif name == columns.weight:
            emit(OP_NUM, toff, n_label + 1, 1)
        elif name == columns.uid and capture_uids:
            emit(OP_STR, toff, 0, 1)
        elif name in tag_names:
            emit(OP_STR, toff, 1 + tag_names.index(name), 0)
        elif name == "metadataMap" and tag_names and _is_map_like(f["type"], names):
            args = [OP_META, toff, len(tag_names)]
            for ti in range(len(tag_names)):
                args += [1 + ti, ti]
            emit(*args)
        elif name in bag_shards:
            rec = _find_bag_record(f["type"], names)
            rfields = [rf["name"] for rf in rec.get("fields", ())]
            if "name" not in rfields or "value" not in rfields:
                raise Unsupported(
                    f"feature bag {name!r} items lack name/value fields"
                )
            npos = rfields.index("name")
            tpos = rfields.index("term") if "term" in rfields else -1
            vpos = rfields.index("value")
            fast = 1 if _is_fast_bag(rec, names) else 0
            shards = bag_shards[name]
            emit(OP_BAG, toff, npos, tpos, vpos, fast, len(shards), *shards)
        else:
            emit(OP_SKIP, toff)

    # An index map of None marks a COLLECT shard (index build): the decoder
    # interns every decoded feature key instead of probing a table.
    tables = [
        None if index_maps[s] is None else _build_table(index_maps[s])
        for s in shard_order
    ]
    return Program(
        ttree=np.asarray(ttree, np.int32),
        ops=np.asarray(ops, np.int32),
        op_starts=np.asarray(op_starts, np.int64),
        num_names=num_names,
        null_defaults=null_defaults,
        str_names=str_names,
        tag_names=tag_names,
        shard_order=shard_order,
        tables=tables,
        n_label_cols=n_label,
    )


# ---------------------------------------------------------------------------
# chunks


class DictColumn:
    """Dictionary-encoded string column: ``values[codes[i]]``; code -1 means
    unset (maps to the materialize default).

    ``values`` is LAZY: unique strings decode from the native dictionary only
    when first accessed, so flows that never read uids/tags as strings (bulk
    training) pay nothing for them. Codes always index a prefix of the final
    dictionary (it grows monotonically across the stream), so resolving late
    is safe."""

    def __init__(self, codes: np.ndarray, values):
        self.codes = codes
        self._values = values      # np.ndarray | zero-arg callable

    @property
    def values(self) -> np.ndarray:
        if callable(self._values):
            self._values = self._values()
        return self._values

    def materialize(self, default: str = "") -> np.ndarray:
        ext = np.concatenate([self.values, np.array([default], object)])
        return ext[self.codes]


@dataclasses.dataclass
class GameDataChunk:
    """One streamed chunk: columnar NumPy + padded-ELL features per shard."""

    labels: np.ndarray           # float64 [n] (NaN = missing)
    offsets: np.ndarray          # float64 [n]
    weights: np.ndarray          # float64 [n]
    uids: DictColumn
    id_tags: dict                # tag -> DictColumn
    features: dict               # shard -> SparseFeatures (numpy-backed)

    @property
    def n_rows(self) -> int:
        return len(self.labels)

    def to_bundle(
        self,
        pad_rows_to: int = 0,
        pad_nnz_to: Optional[Mapping[str, int]] = None,
    ):
        """This chunk as a ``GameDataBundle`` (device features, materialized
        string columns) — the unit of chunked scoring.

        ``pad_rows_to`` / ``pad_nnz_to`` stabilize the jit shapes across
        chunks (each distinct (rows, K) pair costs one XLA compile): padded
        rows carry weight 0, ghost features, empty uid/tags; callers slice
        outputs back to ``n_rows``.
        """
        from photon_tpu.io.data_reader import GameDataBundle

        n = self.n_rows
        n_pad = max(pad_rows_to, n)

        def pad1(a, fill=0.0):
            return np.pad(a, (0, n_pad - n), constant_values=fill) \
                if n_pad > n else a

        features = {}
        for s, sf in self.features.items():
            k_pad = max((pad_nnz_to or {}).get(s, 0), sf.idx.shape[1])
            iarr, varr = sf.idx, sf.val
            if n_pad > n or k_pad > iarr.shape[1]:
                grown_i = np.full((n_pad, k_pad), sf.dim, np.int32)
                grown_v = np.zeros((n_pad, k_pad), varr.dtype)
                grown_i[:n, : iarr.shape[1]] = iarr
                grown_v[:n, : varr.shape[1]] = varr
                iarr, varr = grown_i, grown_v
            import jax.numpy as jnp

            features[s] = SparseFeatures(
                idx=jnp.asarray(iarr), val=jnp.asarray(varr), dim=sf.dim
            )
        weights = self.weights
        if n_pad > n:
            weights = np.pad(weights, (0, n_pad - n))  # padded rows weight 0
        return GameDataBundle(
            features=features,
            labels=pad1(self.labels, np.nan),
            offsets=pad1(self.offsets),
            weights=weights,
            uids=np.concatenate([
                self.uids.materialize(""),
                np.full(n_pad - n, "", object),
            ]) if n_pad > n else self.uids.materialize("").astype(object),
            id_tags={
                t: np.concatenate([
                    c.materialize(), np.full(n_pad - n, "", object)
                ]) if n_pad > n else c.materialize().astype(object)
                for t, c in self.id_tags.items()
            },
        )

    def split(self, n_parts: int) -> list["GameDataChunk"]:
        """Contiguous row split for per-device host pre-sharding (the
        reference pre-shards input files across executors; SURVEY.md §2.6)."""
        bounds = np.linspace(0, self.n_rows, n_parts + 1).astype(int)
        out = []
        for a, b in zip(bounds, bounds[1:]):
            out.append(GameDataChunk(
                labels=self.labels[a:b],
                offsets=self.offsets[a:b],
                weights=self.weights[a:b],
                uids=DictColumn(self.uids.codes[a:b], self.uids.values),
                id_tags={
                    t: DictColumn(c.codes[a:b], c.values)
                    for t, c in self.id_tags.items()
                },
                features={
                    s: SparseFeatures(
                        idx=sf.idx[a:b], val=sf.val[a:b], dim=sf.dim
                    )
                    for s, sf in self.features.items()
                },
            ))
        return out


def ell_from_triples(
    rows: np.ndarray,
    idx: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    dim: int,
    dtype=np.float32,
    intercept_index: Optional[int] = None,
) -> SparseFeatures:
    """Vectorized (row, col, value) triples -> padded ELL. ``rows`` must be
    row-major ordered (the decoder emits them that way)."""
    base = 1 if intercept_index is not None and intercept_index >= 0 else 0
    counts = np.bincount(rows, minlength=n_rows) if len(rows) else np.zeros(
        n_rows, np.int64
    )
    k = int(counts.max()) + base if n_rows else base
    k = max(k, 1)
    iarr = np.full((n_rows, k), dim, np.int32)
    varr = np.zeros((n_rows, k), np.dtype(dtype))
    if len(rows):
        scatter = _ell_scatter_fn(varr.dtype)
        if scatter is not None:
            fn, out_ctype = scatter
            rows32 = np.ascontiguousarray(rows, np.int32)
            idx32 = np.ascontiguousarray(idx, np.int32)
            vals64 = np.ascontiguousarray(vals, np.float64)
            fn(
                _np_ptr(rows32, ctypes.c_int32),
                _np_ptr(idx32, ctypes.c_int32),
                _np_ptr(vals64, ctypes.c_double),
                len(rows32), k, base,
                _np_ptr(iarr, ctypes.c_int32),
                _np_ptr(varr, out_ctype),
            )
        else:
            starts = np.zeros(n_rows + 1, np.int64)
            np.cumsum(counts, out=starts[1:])
            pos = np.arange(len(rows), dtype=np.int64) - starts[rows] + base
            iarr[rows, pos] = idx
            varr[rows, pos] = vals.astype(varr.dtype)
    if base:
        iarr[:, 0] = intercept_index
        varr[:, 0] = 1.0
    return SparseFeatures(idx=iarr, val=varr, dim=dim)


def _ell_scatter_fn(dtype: np.dtype):
    """(native scatter fn, output ctype) for float32/float64 outputs, None
    otherwise (fallback to the numpy fancy-index path — e.g. no compiler,
    or exotic dtypes)."""
    from photon_tpu import native

    lib = native.get_lib()
    if lib is None:
        return None
    if dtype == np.float32:
        return lib.ph_ell_scatter_f32, ctypes.c_float
    if dtype == np.float64:
        return lib.ph_ell_scatter_f64, ctypes.c_double
    return None


# ---------------------------------------------------------------------------
# the reader


def _np_ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _read_dict_range(state, index, start, size_fn, bytes_fn, range_fn):
    """Fetch entries [start, size) of one native StrDict (string column or
    collect-mode shard keys) as utf-8 strings — the one Python side of the
    incremental dict-range protocol."""
    n = size_fn(state, index)
    if n <= start:
        return n, []
    hb = bytes_fn(state, index, start)
    heap = np.empty(max(hb, 1), np.uint8)
    offs = np.empty(n - start + 1, np.int64)
    range_fn(state, index, start, _np_ptr(heap, ctypes.c_uint8),
             _np_ptr(offs, ctypes.c_int64))
    raw = heap.tobytes()
    return n, [
        raw[offs[i]:offs[i + 1]].decode("utf-8") for i in range(n - start)
    ]


class NativeDecoder:
    """ctypes wrapper around one avro_block.cc State."""

    def __init__(self, lib, program: Program):
        self.lib = lib
        self.program = program
        self._dict_cache: dict = {}
        p = program
        tag_blob = b"".join(t.encode() for t in p.tag_names)
        tag_offs = np.zeros(len(p.tag_names) + 1, np.int64)
        np.cumsum([len(t.encode()) for t in p.tag_names], out=tag_offs[1:])
        tag_arr = (
            np.frombuffer(tag_blob, np.uint8)
            if tag_blob
            else np.zeros(1, np.uint8)
        )
        n_shards = len(p.tables)
        hash_ptrs = (ctypes.POINTER(ctypes.c_uint64) * max(n_shards, 1))()
        val_ptrs = (ctypes.POINTER(ctypes.c_int32) * max(n_shards, 1))()
        sizes = np.zeros(max(n_shards, 1), np.int64)
        self._keepalive = [tag_offs, tag_arr, sizes]
        for i, table in enumerate(p.tables):
            if table is None:  # collect (index-build) shard
                sizes[i] = -1
                continue
            th, tv = table
            hash_ptrs[i] = _np_ptr(th, ctypes.c_uint64)
            val_ptrs[i] = _np_ptr(tv, ctypes.c_int32)
            sizes[i] = len(th)
            self._keepalive += [th, tv]
        self.state = lib.ph_create(
            _np_ptr(p.ttree, ctypes.c_int32), len(p.ttree),
            _np_ptr(p.ops, ctypes.c_int32), len(p.ops),
            _np_ptr(p.op_starts, ctypes.c_int64), len(p.op_starts),
            len(p.num_names), _np_ptr(p.null_defaults, ctypes.c_double),
            len(p.str_names),
            _np_ptr(tag_arr, ctypes.c_uint8),
            _np_ptr(tag_offs, ctypes.c_int64), len(p.tag_names),
            n_shards, hash_ptrs, val_ptrs, _np_ptr(sizes, ctypes.c_int64),
        )
        if not self.state:
            raise MemoryError("ph_create failed")

    def decode_block(self, payload: bytes, count: int) -> int:
        arr = np.frombuffer(payload, np.uint8) if payload else np.zeros(1, np.uint8)
        r = self.lib.ph_decode_block(
            self.state, _np_ptr(arr, ctypes.c_uint8), len(payload), count
        )
        if r == -7:
            # bad_alloc caught at the native ABI boundary (the alternative
            # was std::terminate -> a fatal interpreter abort). The chunk
            # state is incoherent; the stream must abort, not continue.
            raise MemoryError("native avro decode: allocation failed "
                              "(host out of memory?)")
        if r < 0:
            raise SchemaError(
                f"native avro decode failed: {_ERRORS.get(r, r)}"
            )
        return r

    def take_chunk(self, ell: Optional[dict] = None,
                   ell_dtype=np.float32) -> dict:
        """Snapshot current buffers as numpy arrays and reset row state.

        ``ell`` maps shard name -> ``(dim, intercept_index_or_None)``: those
        shards come back as ASSEMBLED ELL arrays (``"ell"`` key, built by
        one native pass that writes entries and ghost padding directly —
        no triples copy, no bincount, no fill pass). Shards not in ``ell``
        come back as triples, as before.
        """
        lib, st = self.lib, self.state
        n = lib.ph_chunk_rows(st)
        p = self.program
        num = {}
        for c, name in enumerate(p.num_names):
            a = np.empty(n, np.float64)
            if n:
                lib.ph_get_num_col(st, c, _np_ptr(a, ctypes.c_double))
            num[name] = a
        codes = {}
        for c, name in enumerate(p.str_names):
            a = np.empty(n, np.int32)
            if n:
                lib.ph_get_str_codes(st, c, _np_ptr(a, ctypes.c_int32))
            codes[name] = a
        if ell is not None:
            dt = np.dtype(ell_dtype)
            fill = (lib.ph_shard_ell_f32 if dt == np.float32
                    else lib.ph_shard_ell_f64 if dt == np.float64 else None)
            if fill is None:
                ell = None  # exotic dtype: triples fallback below
        triples = {}
        ells = {}
        for si, shard in enumerate(p.shard_order):
            if ell is not None and shard in ell:
                dim, icol = ell[shard]
                base = 1 if (icol is not None and icol >= 0) else 0
                k = max(int(lib.ph_shard_max_run(st, si)) + base, 1)
                iarr = np.empty((n, k), np.int32)
                varr = np.empty((n, k), dt)
                out_ct = (ctypes.c_float if dt == np.float32
                          else ctypes.c_double)
                if n:
                    fill(st, si, n, k,
                         icol if base else -1, dim,
                         _np_ptr(iarr, ctypes.c_int32),
                         _np_ptr(varr, out_ct))
                ells[shard] = SparseFeatures(idx=iarr, val=varr, dim=dim)
                continue
            m = lib.ph_shard_nnz(st, si)
            rows = np.empty(m, np.int32)
            idx = np.empty(m, np.int32)
            val = np.empty(m, np.float64)
            if m:
                lib.ph_get_shard_triples(
                    st, si, _np_ptr(rows, ctypes.c_int32),
                    _np_ptr(idx, ctypes.c_int32), _np_ptr(val, ctypes.c_double),
                )
            triples[shard] = (rows, idx, val)
        lib.ph_reset_chunk(st)
        return {"n": n, "num": num, "codes": codes, "triples": triples,
                "ell": ells}

    def dictionaries(self) -> dict:
        """Current per-column unique-string arrays. Dictionaries only grow,
        so each call decodes just the entries added since the last one."""
        out = {}
        for c, name in enumerate(self.program.str_names):
            cache = self._dict_cache.setdefault(name, [])
            _, new_entries = _read_dict_range(
                self.state, c, len(cache),
                self.lib.ph_dict_size,
                self.lib.ph_dict_heap_bytes_from,
                self.lib.ph_get_dict_range,
            )
            cache.extend(new_entries)
            out[name] = np.array(cache, object)
        return out

    def __del__(self):
        if getattr(self, "state", None):
            self.lib.ph_destroy(self.state)
            self.state = None


def iter_container_blocks(path: str):
    """(schema, codec, iterator of (payload_bytes, record_count)) — the
    container framing from io/avro.py, without record decode."""
    import io as _io
    import json
    import zlib

    with open(path, "rb") as f:
        if f.read(4) != avro.MAGIC:
            raise SchemaError(f"{path}: not an Avro object container file")
        head = f.read(1 << 16)
        mdec = avro.Decoder({"type": "map", "values": "bytes"})
        while True:
            try:
                meta, pos = mdec.decode(head)
                break
            except IndexError:
                more = f.read(1 << 16)
                if not more:
                    raise SchemaError(f"{path}: truncated container header") from None
                head += more
        schema = json.loads(meta["avro.schema"])
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise SchemaError(f"unsupported codec {codec!r}")
        f.seek(4 + pos)
        sync = f.read(avro.SYNC_SIZE)
        data_start = 4 + pos + avro.SYNC_SIZE

    def _mm_varint(mm, pos, end):
        # Shared wire-format decode (avro._read_long) with container-level
        # error mapping; bounds violations surface as IndexError there.
        try:
            v, pos = avro._read_long(mm, pos)
        except IndexError:
            raise SchemaError(f"{path}: truncated avro container") from None
        if pos > end:
            raise SchemaError(f"{path}: truncated avro container")
        return v, pos

    def blocks():
        import zlib

        if codec == "null":
            # Zero-copy: the payload slices are memoryviews over the mmap
            # (the native decoder reads them in place via np.frombuffer) —
            # no kernel read()+copy per block. The mmap stays alive through
            # each yielded slice's refcount.
            import mmap as _mmap

            with open(path, "rb") as f:
                try:
                    mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
                except (ValueError, OSError):
                    mm = None  # empty file / no-mmap fs: buffered fallback
            if mm is not None:
                # No explicit close: a consumer may legitimately hold the
                # last yielded slice past exhaustion, and mmap.close() with
                # exported buffers raises BufferError — refcounting closes
                # the map once every slice drops.
                view = memoryview(mm)
                pos, end = data_start, len(mm)
                while pos < end:
                    count, pos = _mm_varint(mm, pos, end)
                    size, pos = _mm_varint(mm, pos, end)
                    # Negative zigzag decodes would slice from the END of
                    # the map and walk pos backward (hang/garbage) — corrupt
                    # input must fail loud instead.
                    if count < 0 or size < 0 or pos + size > end:
                        raise SchemaError(
                            f"{path}: corrupt avro block header "
                            f"(count={count}, size={size})"
                        )
                    yield view[pos:pos + size], count
                    pos += size
                    if bytes(mm[pos:pos + avro.SYNC_SIZE]) != sync:
                        raise SchemaError(f"{path}: sync marker mismatch")
                    pos += avro.SYNC_SIZE
                return
        with open(path, "rb") as f:
            f.seek(data_start)
            while True:
                hdr = f.read(1)
                if not hdr:
                    return
                count = avro._stream_varint(f, hdr)
                hdr = f.read(1)
                if not hdr:
                    raise SchemaError("truncated avro container")
                size = avro._stream_varint(f, hdr)
                payload = f.read(size)
                if len(payload) < size:
                    raise SchemaError(f"{path}: truncated block payload")
                if codec == "deflate":
                    payload = zlib.decompress(payload, wbits=-15)
                yield payload, count
                if f.read(avro.SYNC_SIZE) != sync:
                    raise SchemaError(f"{path}: sync marker mismatch")

    return schema, codec, blocks()


def iter_blocks_with_retry(
    path: str,
    retries: int = 2,
    backoff_s: float = 0.05,
    sleep: Callable[[float], None] = time.sleep,
):
    """``iter_container_blocks`` with bounded retry of transient IO errors.

    A single flaky read (network filesystem hiccup, object-store 5xx
    surfaced as ``OSError``) used to kill the whole ingest. Here each
    transient ``OSError`` — during the header open or mid-stream — reopens
    the container after an exponential backoff and SKIPS the blocks already
    yielded (block framing is positional, so re-reading and discarding the
    prefix is exact; rows already decoded downstream stay valid). After
    ``retries`` reopens the error propagates. ``FileNotFoundError`` never
    retries: a missing input is a config bug, not a hiccup.

    The per-block ``io.block_read`` fault point lives here, so injected
    faults exercise exactly this recovery path.
    """
    attempt = 0
    while True:
        try:
            schema, codec, blocks = iter_container_blocks(path)
            break
        except FileNotFoundError:
            raise
        except OSError as e:
            attempt += 1
            if attempt > retries:
                raise
            logger.warning(
                "transient open error on %s (%s); retry %d/%d",
                path, e, attempt, retries,
            )
            sleep(backoff_s * (2 ** (attempt - 1)))

    def gen():
        nonlocal blocks
        attempts = attempt
        yielded = 0
        while True:
            try:
                if blocks is None:
                    # Reopen INSIDE the protected region: during a real
                    # outage the reopen is the call most likely to fail,
                    # and it must draw on the same retry budget.
                    _, _, blocks = iter_container_blocks(path)
                skip = yielded
                for payload, count in blocks:
                    if skip:
                        skip -= 1
                        continue
                    fault_point("io.block_read", path=path, block=yielded)
                    yield payload, count
                    yielded += 1
                return
            except FileNotFoundError:
                raise
            except OSError as e:
                blocks = None
                attempts += 1
                if attempts > retries:
                    raise
                logger.warning(
                    "transient read error on %s block %d (%s); retry %d/%d",
                    path, yielded, e, attempts, retries,
                )
                sleep(backoff_s * (2 ** (attempts - 1)))

    return schema, codec, gen()


def collect_feature_keys(
    paths,
    shard_configs: Mapping[str, object],
    columns=None,
    file_shard: Optional[tuple[int, int]] = None,
    reset_every_rows: int = 1 << 20,
) -> dict:
    """Native-speed feature-index build: one streaming pass that interns
    every decoded ``(name, term)`` into per-shard first-seen-order key sets
    (the reference's distributed ⟦FeatureIndexingDriver⟧ scan, SURVEY.md
    §2.3, at block-decoder throughput instead of per-record Python).

    Returns ``{shard: [(name, term), ...]}`` in first-seen order. Raises
    :class:`Unsupported` when the native decoder or schema dialect is
    unavailable — callers fall back to the per-record scan.
    """
    import json

    from photon_tpu.io.data_reader import InputColumnNames, _expand_paths

    lib = native.get_lib()
    if lib is None:
        raise Unsupported("native decoder unavailable")
    columns = columns or InputColumnNames()
    shard_order = sorted(shard_configs)
    files = _expand_paths(paths)
    if file_shard is not None:
        i, n = file_shard
        files = files[i::n]

    out: dict = {s: [] for s in shard_order}
    seen: dict = {s: set() for s in shard_order}

    def drain(dec) -> None:
        # Pull the keys this decoder added since its last drain. Draining
        # after EVERY file keeps the merged output in record-stream
        # first-seen order even when the schema (hence decoder) alternates
        # between files; keys another decoder saw earlier dedupe here.
        for si, shard in enumerate(dec.program.shard_order):
            dec._drained[si], new_keys = _read_dict_range(
                dec.state, si, dec._drained[si],
                lib.ph_shard_dict_size,
                lib.ph_shard_dict_heap_bytes_from,
                lib.ph_shard_dict_range,
            )
            for k in new_keys:
                if k not in seen[shard]:
                    seen[shard].add(k)
                    name, _, term = k.partition("\x01")
                    out[shard].append((name, term))

    decoders: dict = {}
    for path in files:
        schema, _, blocks = iter_container_blocks(path)
        key = json.dumps(schema, sort_keys=True)
        if key not in decoders:
            prog = compile_program(
                schema, columns, shard_configs,
                {s: None for s in shard_order},   # all shards collect
                id_tag_columns=(), capture_uids=False,
            )
            decoders[key] = NativeDecoder(lib, prog)
            decoders[key]._drained = [0] * len(shard_order)
        dec = decoders[key]
        for payload, count in blocks:
            if dec.decode_block(payload, count) >= reset_every_rows:
                # Row buffers are unused here; drop them so host memory is
                # bounded by unique keys, not rows. Key dicts persist.
                lib.ph_reset_chunk(dec.state)
        drain(dec)
    return out


class StreamingAvroReader:
    """Chunked columnar Avro reader sharing AvroDataReader's configuration.

    ``chunk_rows`` bounds host memory: each yielded chunk holds about that
    many rows regardless of dataset size (block boundaries round it up).
    """

    def __init__(
        self,
        index_maps: Mapping[str, IndexMap],
        shard_configs: Optional[Mapping[str, object]] = None,
        columns=None,
        id_tag_columns: Sequence[str] = (),
        chunk_rows: int = 1 << 20,
        capture_uids: bool = True,
        io_retries: int = 2,
        io_retry_backoff_s: float = 0.05,
    ):
        from photon_tpu.io.data_reader import FeatureShardConfig, InputColumnNames

        self.columns = columns or InputColumnNames()
        # Bounded retry of transient OSErrors per input file (see
        # iter_blocks_with_retry); 0 disables.
        self.io_retries = int(io_retries)
        self.io_retry_backoff_s = float(io_retry_backoff_s)
        self.index_maps = dict(index_maps)
        self.shard_configs = dict(shard_configs) if shard_configs else {
            s: FeatureShardConfig(feature_bags=(self.columns.features,))
            for s in self.index_maps
        }
        self.id_tag_columns = tuple(id_tag_columns)
        self.chunk_rows = int(chunk_rows)
        # uid capture costs one dictionary entry per (typically unique) row;
        # bulk training flows that never write scores back can disable it.
        self.capture_uids = bool(capture_uids)
        self._uid_rows_seen = 0
        self._uid_growth_warned = False
        self._intercepts = {
            shard: self.index_maps[shard].get_index(INTERCEPT_NAME, INTERCEPT_TERM)
            for shard, cfg in self.shard_configs.items()
            if cfg.add_intercept
        }
        self._programs: dict = {}   # schema json -> (Program, NativeDecoder)

    # -- core ---------------------------------------------------------------

    def _decoder_for(self, schema) -> NativeDecoder:
        import json

        lib = native.get_lib()
        if lib is None:
            raise Unsupported("native decoder unavailable")
        key = json.dumps(schema, sort_keys=True)
        if key not in self._programs:
            prog = compile_program(
                schema, self.columns, self.shard_configs, self.index_maps,
                self.id_tag_columns, capture_uids=self.capture_uids,
            )
            self._programs[key] = NativeDecoder(lib, prog)
        return self._programs[key]

    def iter_chunks(
        self,
        paths,
        dtype=np.float32,
        require_labels: bool = True,
        file_shard: Optional[tuple[int, int]] = None,
    ) -> Iterator[GameDataChunk]:
        """Stream chunks. ``file_shard=(i, n)`` reads only every n-th file
        starting at i — the host-parallel ingest model (one reader process
        per core, each owning a file subset, exactly how the reference
        spreads file splits over Spark executors; SURVEY.md §2.6)."""
        from photon_tpu.io.data_reader import _expand_paths

        files = _expand_paths(paths)
        if file_shard is not None:
            i, n = file_shard
            files = files[i::n]
        dec: Optional[NativeDecoder] = None
        pending = 0
        for path in files:
            schema, _, blocks = iter_blocks_with_retry(
                path, retries=self.io_retries,
                backoff_s=self.io_retry_backoff_s,
            )
            d = self._decoder_for(schema)
            if dec is not None and d is not dec and pending:
                yield self._finish_chunk(dec, dtype, require_labels)
                pending = 0
            dec = d
            for b_i, (payload, count) in enumerate(blocks):
                # Per-block span (docs/observability.md ingest lane): block
                # decode is the unit of ingest work, and a slow file/fs
                # shows up as widening ingest.block spans on one path.
                with trace_span("ingest.block", cat="ingest", path=path,
                                block=b_i, records=count):
                    pending = dec.decode_block(payload, count)
                if pending >= self.chunk_rows:
                    yield self._finish_chunk(dec, dtype, require_labels)
                    pending = 0
        if dec is not None and pending:
            yield self._finish_chunk(dec, dtype, require_labels)

    def _finish_chunk(self, dec: NativeDecoder, dtype, require_labels) -> GameDataChunk:
        with trace_span("ingest.chunk", cat="ingest") as sp:
            chunk = self._assemble_chunk(dec, dtype, require_labels)
            sp.set(rows=chunk.n_rows)
        self._note_uid_growth(dec, chunk.n_rows)
        return chunk

    def _note_uid_growth(self, dec: NativeDecoder, n_rows: int) -> None:
        """One-time warning when ``capture_uids=True`` has interned enough
        rows that the uid dictionary plausibly dominates host memory (it
        grows with UNIQUE uids, i.e. ~every row on training data — the
        caveat that used to live only in the module docstring). Threshold
        via ``PHOTON_UID_WARN_ROWS`` (rows; 0 disables)."""
        if not self.capture_uids or self._uid_growth_warned:
            return
        self._uid_rows_seen += int(n_rows)
        try:
            threshold = int(os.environ.get("PHOTON_UID_WARN_ROWS",
                                           str(10_000_000)))
        except ValueError:
            threshold = 10_000_000
        if threshold <= 0 or self._uid_rows_seen < threshold:
            return
        self._uid_growth_warned = True
        try:  # "__uid__" is string column 0 by construction (compile_program)
            dict_entries = int(dec.lib.ph_dict_size(dec.state, 0))
        except Exception:  # noqa: BLE001 - the warning must never kill ingest
            dict_entries = -1
        logger.warning(
            "capture_uids=True has streamed %d rows; the uid dictionary "
            "holds %s unique entries and grows with unique uids for the "
            "whole read — pass capture_uids=False on bulk training flows "
            "that never read uids back (PHOTON_UID_WARN_ROWS tunes or "
            "disables this warning)",
            self._uid_rows_seen,
            dict_entries if dict_entries >= 0 else "unknown",
        )

    def _assemble_chunk(self, dec: NativeDecoder, dtype, require_labels) -> GameDataChunk:
        raw = dec.take_chunk(
            ell={
                shard: (len(self.index_maps[shard]),
                        self._intercepts.get(shard))
                for shard in dec.program.shard_order
            },
            ell_dtype=dtype,
        )
        p = dec.program
        n = raw["n"]
        labels = raw["num"][p.num_names[0]]
        # Alias resolution: configured response first, then aliases in order.
        for alias_col in range(1, p.n_label_cols):
            alias = raw["num"][p.num_names[alias_col]]
            missing = np.isnan(labels)
            labels[missing] = alias[missing]
        if require_labels and np.isnan(labels).any():
            bad = int(np.flatnonzero(np.isnan(labels))[0])
            raise ValueError(
                f"record missing required column (response, chunk row {bad}; "
                f"set require_labels=False to admit unlabeled records)"
            )

        def resolver(name):
            return lambda: dec.dictionaries()[name]

        tag_cols = {}
        for t in self.id_tag_columns:
            codes = raw["codes"][t]
            if (codes < 0).any():
                raise ValueError(
                    f"id tag column {t!r} missing from record and metadataMap"
                )
            tag_cols[t] = DictColumn(codes, resolver(t))
        features = {}
        for shard in p.shard_order:
            if shard in raw["ell"]:  # native direct assembly
                features[shard] = raw["ell"][shard]
                continue
            rows, idx, val = raw["triples"][shard]
            features[shard] = ell_from_triples(
                rows, idx, val, n, dim=len(self.index_maps[shard]),
                dtype=dtype, intercept_index=self._intercepts.get(shard),
            )
        return GameDataChunk(
            labels=labels,
            offsets=raw["num"][p.num_names[p.n_label_cols]],
            weights=raw["num"][p.num_names[p.n_label_cols + 1]],
            uids=DictColumn(raw["codes"]["__uid__"], resolver("__uid__")),
            id_tags=tag_cols,
            features=features,
        )

    # -- full-dataset assembly ---------------------------------------------

    def read(self, paths, dtype=np.float32, require_labels: bool = True):
        """Concatenate all chunks into a GameDataBundle (AvroDataReader-
        compatible output, streaming-speed decode)."""
        return chunks_to_bundle(
            list(self.iter_chunks(paths, dtype, require_labels)),
            self.index_maps, self.id_tag_columns, dtype,
        )


def chunks_to_bundle(
    chunks: Sequence[GameDataChunk],
    index_maps: Mapping[str, IndexMap],
    id_tag_columns: Sequence[str],
    dtype=np.float32,
    feed_dtype=None,
):
    """Concatenate streamed chunks (in order) into one GameDataBundle —
    shared by in-process reads and the parallel-ingest reassembly.

    ``feed_dtype`` (e.g. ``"bfloat16"``) narrows the feature VALUE arrays on
    the host before the device upload — the bf16 feed: half the transfer
    bytes, f32 accumulation downstream via dtype promotion (see
    ``io/prefetch.py``)."""
    import jax.numpy as jnp

    from photon_tpu.io.data_reader import GameDataBundle

    if not chunks:
        # Valid zero-record dataset (e.g. an empty scoring partition):
        # an empty bundle, like the per-record reader.
        empty = np.zeros(0, np.float64)
        return GameDataBundle(
            features={
                s: SparseFeatures(
                    idx=jnp.full((0, 1), len(m), jnp.int32),
                    val=jnp.zeros((0, 1), np.dtype(dtype)),
                    dim=len(m),
                )
                for s, m in index_maps.items()
            },
            labels=empty, offsets=empty, weights=empty,
            uids=np.zeros(0, object),
            id_tags={t: np.zeros(0, object) for t in id_tag_columns},
        )
    n = sum(c.n_rows for c in chunks)
    labels = np.concatenate([c.labels for c in chunks])
    offsets = np.concatenate([c.offsets for c in chunks])
    weights = np.concatenate([c.weights for c in chunks])
    uids = np.concatenate([c.uids.materialize("") for c in chunks])
    id_tags = {
        t: np.concatenate([c.id_tags[t].materialize() for c in chunks])
        for t in id_tag_columns
    }
    features = {}
    for shard in index_maps:
        dim = len(index_maps[shard])
        k = max(c.features[shard].idx.shape[1] for c in chunks)
        iarr = np.full((n, k), dim, np.int32)
        varr = np.zeros((n, k), np.dtype(dtype))
        at = 0
        for c in chunks:
            sf = c.features[shard]
            m, kk = sf.idx.shape
            iarr[at:at + m, :kk] = sf.idx
            varr[at:at + m, :kk] = sf.val
            at += m
        if feed_dtype is not None:
            from photon_tpu.io.prefetch import host_feed_array

            varr = host_feed_array(varr, feed_dtype)
        features[shard] = SparseFeatures(
            idx=jnp.asarray(iarr), val=jnp.asarray(varr), dim=dim
        )
    return GameDataBundle(
        features=features,
        labels=labels,
        offsets=offsets,
        weights=weights,
        uids=uids.astype(object),
        id_tags=id_tags,
    )
