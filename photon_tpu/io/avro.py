"""Avro binary codec + object container files, from scratch.

Parity: the reference stores ALL data and models as Avro on HDFS
(⟦photon-client/.../data/avro/AvroUtils.scala⟧, ⟦photon-avro-schemas/⟧ —
SURVEY.md §2.3/§2.4). No Avro library ships in this image, so this module
implements the Avro 1.x specification directly:

* primitive binary encodings — zigzag-varint ``int``/``long``, little-endian
  IEEE ``float``/``double``, length-prefixed ``bytes``/``string``;
* complex types — records (fields in declaration order), enums (index),
  arrays/maps (blocks terminated by count 0), unions (branch index then
  value), fixed;
* object container files — ``Obj\\x01`` magic, file-metadata map carrying the
  writer schema JSON + codec, 16-byte sync marker, and data blocks of
  (record count, byte length, payload, sync); ``null`` and ``deflate``
  (raw zlib) codecs.

Python values map naturally: records ↔ dicts, arrays ↔ lists, maps ↔ dicts,
enums ↔ strings, null union branches ↔ None. Schemas are plain parsed-JSON
dicts; named-type references are resolved through a registry so photon's
nested ``NameTermValueAvro`` reuse works.

This module is the reference implementation and the always-available
fallback for the hot decode path.
"""
from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator, Optional, Union

MAGIC = b"Obj\x01"
SYNC_SIZE = 16

_PRIMITIVES = frozenset(
    ("null", "boolean", "int", "long", "float", "double", "bytes", "string")
)

Schema = Union[str, dict, list]


# ---------------------------------------------------------------------------
# schema handling


class SchemaError(ValueError):
    pass


def parse_schema(schema: Union[str, Schema]) -> Schema:
    """Accept a JSON string or an already-parsed schema object."""
    if isinstance(schema, str) and schema.lstrip().startswith(("{", "[", '"')):
        return json.loads(schema)
    return schema


def _collect_names(schema: Schema, names: dict) -> None:
    """Register named types (record/enum/fixed) for by-name references."""
    if isinstance(schema, list):
        for s in schema:
            _collect_names(s, names)
    elif isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            name = schema["name"]
            ns = schema.get("namespace")
            full = f"{ns}.{name}" if ns and "." not in name else name
            names[full] = schema
            names[name.split(".")[-1]] = schema
        if t == "record":
            for f in schema.get("fields", ()):
                _collect_names(f["type"], names)
        elif t == "array":
            _collect_names(schema["items"], names)
        elif t == "map":
            _collect_names(schema["values"], names)


def _resolve(schema: Schema, names: dict) -> Schema:
    if isinstance(schema, str) and schema not in _PRIMITIVES:
        try:
            return names[schema]
        except KeyError:
            raise SchemaError(f"unresolved named type {schema!r}") from None
    if isinstance(schema, dict) and isinstance(schema.get("type"), str) and (
        schema["type"] not in _PRIMITIVES
        and schema["type"] not in ("record", "enum", "fixed", "array", "map")
    ):
        return _resolve(schema["type"], names)
    return schema


# ---------------------------------------------------------------------------
# primitive binary encoding


def _write_long(out: BinaryIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def _read_long(buf: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


# ---------------------------------------------------------------------------
# schema-driven encode


class Encoder:
    def __init__(self, schema: Union[str, Schema]):
        self.schema = parse_schema(schema)
        self.names: dict = {}
        _collect_names(self.schema, self.names)

    def encode(self, value: Any, out: Optional[BinaryIO] = None) -> bytes:
        buf = out or io.BytesIO()
        self._enc(self.schema, value, buf)
        return b"" if out is not None else buf.getvalue()

    def _enc(self, schema: Schema, v: Any, out: BinaryIO) -> None:
        schema = _resolve(schema, self.names)
        if isinstance(schema, list):  # union
            for i, branch in enumerate(schema):
                if _union_match(_resolve(branch, self.names), v):
                    _write_long(out, i)
                    self._enc(branch, v, out)
                    return
            raise SchemaError(f"value {v!r} matches no union branch {schema}")
        t = schema if isinstance(schema, str) else schema["type"]
        if t == "null":
            return
        if t == "boolean":
            out.write(b"\x01" if v else b"\x00")
        elif t in ("int", "long"):
            _write_long(out, int(v))
        elif t == "float":
            out.write(struct.pack("<f", float(v)))
        elif t == "double":
            out.write(struct.pack("<d", float(v)))
        elif t == "bytes":
            _write_long(out, len(v))
            out.write(v)
        elif t == "string":
            b = v.encode("utf-8")
            _write_long(out, len(b))
            out.write(b)
        elif t == "fixed":
            if len(v) != schema["size"]:
                raise SchemaError("fixed size mismatch")
            out.write(v)
        elif t == "enum":
            _write_long(out, schema["symbols"].index(v))
        elif t == "array":
            if v:
                _write_long(out, len(v))
                for item in v:
                    self._enc(schema["items"], item, out)
            _write_long(out, 0)
        elif t == "map":
            if v:
                _write_long(out, len(v))
                for k, item in v.items():
                    self._enc("string", k, out)
                    self._enc(schema["values"], item, out)
            _write_long(out, 0)
        elif t == "record":
            for f in schema["fields"]:
                name = f["name"]
                if name in v:
                    fv = v[name]
                elif "default" in f:
                    fv = f["default"]
                else:
                    raise SchemaError(f"missing field {name!r} with no default")
                self._enc(f["type"], fv, out)
        else:
            raise SchemaError(f"unknown type {t!r}")


def _union_match(schema: Schema, v: Any) -> bool:
    t = schema if isinstance(schema, str) else (
        schema[0] if isinstance(schema, list) else schema["type"]
    )
    if t == "null":
        return v is None
    if v is None:
        return False
    if t == "boolean":
        return isinstance(v, bool)
    if t in ("int", "long"):
        return isinstance(v, int) and not isinstance(v, bool)
    if t in ("float", "double"):
        return isinstance(v, float) or (
            isinstance(v, int) and not isinstance(v, bool)
        )
    if t in ("bytes", "fixed"):
        return isinstance(v, (bytes, bytearray))
    if t in ("string", "enum"):
        return isinstance(v, str)
    if t == "array":
        return isinstance(v, (list, tuple))
    if t in ("map", "record"):
        return isinstance(v, dict)
    return True


# ---------------------------------------------------------------------------
# schema-driven decode


class Decoder:
    def __init__(self, schema: Union[str, Schema]):
        self.schema = parse_schema(schema)
        self.names: dict = {}
        _collect_names(self.schema, self.names)

    def decode(self, data: Union[bytes, memoryview], pos: int = 0) -> tuple[Any, int]:
        return self._dec(self.schema, memoryview(data), pos)

    def _dec(self, schema: Schema, buf: memoryview, pos: int) -> tuple[Any, int]:
        schema = _resolve(schema, self.names)
        if isinstance(schema, list):  # union
            idx, pos = _read_long(buf, pos)
            return self._dec(schema[idx], buf, pos)
        t = schema if isinstance(schema, str) else schema["type"]
        if t == "null":
            return None, pos
        if t == "boolean":
            return buf[pos] != 0, pos + 1
        if t in ("int", "long"):
            return _read_long(buf, pos)
        if t == "float":
            return struct.unpack_from("<f", buf, pos)[0], pos + 4
        if t == "double":
            return struct.unpack_from("<d", buf, pos)[0], pos + 8
        if t == "bytes":
            n, pos = _read_long(buf, pos)
            return bytes(buf[pos : pos + n]), pos + n
        if t == "string":
            n, pos = _read_long(buf, pos)
            return str(buf[pos : pos + n], "utf-8"), pos + n
        if t == "fixed":
            n = schema["size"]
            return bytes(buf[pos : pos + n]), pos + n
        if t == "enum":
            i, pos = _read_long(buf, pos)
            return schema["symbols"][i], pos
        if t == "array":
            out = []
            while True:
                count, pos = _read_long(buf, pos)
                if count == 0:
                    return out, pos
                if count < 0:  # block with byte size
                    _, pos = _read_long(buf, pos)
                    count = -count
                for _ in range(count):
                    item, pos = self._dec(schema["items"], buf, pos)
                    out.append(item)
        if t == "map":
            out = {}
            while True:
                count, pos = _read_long(buf, pos)
                if count == 0:
                    return out, pos
                if count < 0:
                    _, pos = _read_long(buf, pos)
                    count = -count
                for _ in range(count):
                    k, pos = self._dec("string", buf, pos)
                    out[k], pos = self._dec(schema["values"], buf, pos)
        if t == "record":
            rec = {}
            for f in schema["fields"]:
                rec[f["name"]], pos = self._dec(f["type"], buf, pos)
            return rec, pos
        raise SchemaError(f"unknown type {t!r}")


# ---------------------------------------------------------------------------
# object container files


class ContainerWriter:
    """Incremental Avro object-container writer: header on open, records
    appended across calls in sync-marked blocks — the streaming form of
    :func:`write_container` (chunked scoring writes scores as they are
    computed instead of materializing every record first)."""

    def __init__(
        self,
        path: str,
        schema: Union[str, Schema],
        codec: str = "null",
        block_records: int = 4096,
        sync: Optional[bytes] = None,
    ):
        if codec not in ("null", "deflate"):
            raise SchemaError(f"unsupported codec {codec!r}")
        self.schema = parse_schema(schema)
        self._enc = Encoder(self.schema)
        self._sync = sync or os.urandom(SYNC_SIZE)
        self._codec = codec
        self._block_records = block_records
        self._block = io.BytesIO()
        self._count = 0
        self.n_written = 0
        self._path = path
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        meta = {
            "avro.schema": json.dumps(self.schema).encode(),
            "avro.codec": codec.encode(),
        }
        menc = Encoder({"type": "map", "values": "bytes"})
        self._f.write(menc.encode(meta))
        self._f.write(self._sync)

    def _flush_block(self) -> None:
        if self._count == 0:
            return
        payload = self._block.getvalue()
        if self._codec == "deflate":
            payload = zlib.compress(payload)[2:-4]  # raw deflate, no hdr/cksum
        hdr = io.BytesIO()
        _write_long(hdr, self._count)
        _write_long(hdr, len(payload))
        self._f.write(hdr.getvalue())
        self._f.write(payload)
        self._f.write(self._sync)
        self._block.seek(0)
        self._block.truncate()
        self._count = 0

    def write(self, rec: Any) -> None:
        # Roll back on mid-record encode failure (e.g. a union mismatch in a
        # later field): partial bytes would otherwise poison the block and
        # corrupt every subsequent record when flushed.
        start = self._block.tell()
        try:
            self._enc.encode(rec, out=self._block)
        except Exception:
            self._block.seek(start)
            self._block.truncate()
            raise
        self._count += 1
        self.n_written += 1
        if self._count >= self._block_records:
            self._flush_block()

    def write_many(self, records: Iterable[Any]) -> int:
        for rec in records:
            self.write(rec)
        return self.n_written

    def close(self) -> None:
        if self._f is not None:
            self._flush_block()
            self._f.close()
            self._f = None

    def abort(self) -> None:
        """Close WITHOUT flushing the buffered block and rename the output to
        ``<path>.partial``.

        Avro containers have no end marker, so a flushed-then-abandoned file
        is indistinguishable from complete output; an aborted chunked run
        must not leave a well-formed partial file under the final name.
        """
        if self._f is not None:
            self._f.close()
            self._f = None
            try:
                os.replace(self._path, self._path + ".partial")
            except OSError:
                pass  # unlinked/moved underneath us; nothing to mark

    def __enter__(self) -> "ContainerWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.abort()
        else:
            self.close()


def write_container(
    path: str,
    schema: Union[str, Schema],
    records: Iterable[Any],
    codec: str = "null",
    block_records: int = 4096,
    sync: Optional[bytes] = None,
) -> int:
    """Write an Avro object container file; returns the record count."""
    with ContainerWriter(path, schema, codec, block_records, sync) as w:
        return w.write_many(records)


def _stream_varint(f, first: bytes) -> int:
    # varint (non-zigzag framing handled by _read_long) from the raw
    # stream; EOF mid-varint means a truncated container, not a spin.
    buf = bytearray(first)
    while buf[-1] & 0x80:
        b = f.read(1)
        if not b:
            raise SchemaError("truncated avro container (EOF mid-varint)")
        buf += b
    v, _ = _read_long(memoryview(bytes(buf)), 0)
    return v


def read_container(path: str) -> tuple[Schema, Iterator[Any]]:
    """Read an Avro object container file → (writer schema, record iterator).

    The header is parsed eagerly under its own file handle (schema-only
    callers leak nothing); the returned iterator opens the file again when
    first advanced.
    """
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise SchemaError(f"{path}: not an Avro object container file")
        # Decode the metadata map incrementally from the head of the file.
        head = f.read(1 << 16)
        mdec = Decoder({"type": "map", "values": "bytes"})
        while True:
            try:
                meta, pos = mdec.decode(head)
                break
            except IndexError:  # metadata longer than the head buffer
                more = f.read(1 << 16)
                if not more:
                    raise SchemaError(f"{path}: truncated container header") from None
                head += more
        if "avro.schema" not in meta:
            raise SchemaError(f"{path}: container header missing avro.schema")
        schema = json.loads(meta["avro.schema"])
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise SchemaError(f"unsupported codec {codec!r}")
        f.seek(4 + pos)
        sync = f.read(SYNC_SIZE)
        data_start = 4 + pos + SYNC_SIZE
    dec = Decoder(schema)

    def records() -> Iterator[Any]:
        with open(path, "rb") as f:
            f.seek(data_start)
            while True:
                hdr = f.read(1)
                if not hdr:
                    return
                count = _stream_varint(f, hdr)
                hdr = f.read(1)
                if not hdr:
                    raise SchemaError(
                        "truncated avro container (EOF before block size)"
                    )
                size = _stream_varint(f, hdr)
                payload = f.read(size)
                if len(payload) < size:
                    raise SchemaError(
                        f"{path}: truncated avro container (block payload "
                        f"{len(payload)} < {size} bytes)"
                    )
                if codec == "deflate":
                    payload = zlib.decompress(payload, wbits=-15)
                mv = memoryview(payload)
                pos = 0
                for _ in range(count):
                    rec, pos = dec.decode(mv, pos)
                    yield rec
                if f.read(SYNC_SIZE) != sync:
                    raise SchemaError(f"{path}: sync marker mismatch (corrupt block)")

    return schema, records()


def read_records(path: str) -> list[Any]:
    """Convenience: fully materialize a container file's records."""
    _, it = read_container(path)
    return list(it)
