"""GAME model directory save/load in the reference's Avro layout.

Parity: reference ⟦photon-client/.../data/avro/ModelProcessingUtils.scala,
AvroUtils, ScoreProcessingUtils⟧ (SURVEY.md §2.3 "Model I/O"):

    model-dir/
      game-metadata.json                      (coordinate → type/shard/task)
      fixed-effect/<coord>/coefficients.avro  1 BayesianLinearModelAvro
      random-effect/<coord>/part-00000.avro   1 record per entity
      scores .avro via save_scores            ScoringResultAvro
      feature summary via save_feature_summary

Coefficients are stored as (name, term, value) lists resolved through the
shard's IndexMap — the on-disk format is index-free, so models survive
re-indexing, exactly the property the reference's Avro layout provides.
Loading a random-effect coordinate reconstructs a ``RandomEffectModel`` with
one synthetic bucket (per-entity sparse vectors padded to a common width);
all scoring/projection paths accept it like a trained model.
"""
from __future__ import annotations

import json
import math
import os
from typing import Mapping, Optional

import jax.numpy as jnp
import numpy as np

from photon_tpu.game.coordinates import FixedEffectModel
from photon_tpu.game.descent import GameModel
from photon_tpu.game.random_effect import RandomEffectModel
from photon_tpu.index.index_map import IndexMap
from photon_tpu.io.avro import read_records, write_container
from photon_tpu.io.schemas import (
    BAYESIAN_LINEAR_MODEL_AVRO,
    FEATURE_SUMMARIZATION_RESULT_AVRO,
    SCORING_RESULT_AVRO,
)
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.types import TaskType

_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}

_META = "game-metadata.json"


def default_index_root(model_dir: str) -> str:
    """Index-store root for a training-driver model directory.

    The training driver writes indexes at ``<out>/index`` while models live
    at ``<out>/best`` or ``<out>/models/<i>`` — walk up past "models", but
    only for true ``models/<i>`` children (an output dir itself named
    "models" must not trigger the walk-up). Shared by the batch scoring
    driver and the serving registry so the two resolve identically.
    """
    norm = os.path.normpath(model_dir)
    parent = os.path.dirname(norm)
    if (os.path.basename(parent) == "models"
            and os.path.basename(norm).isdigit()):
        parent = os.path.dirname(parent)
    return os.path.join(parent, "index")


def _nt_list(imap: IndexMap, indices, values) -> list[dict]:
    out = []
    for i, v in zip(indices, values):
        v = float(v)
        if v == 0.0 or math.isnan(v):
            continue
        name, term = imap.get_feature(int(i))
        out.append({"name": name, "term": term, "value": v})
    return out


def _from_nt_list(imap: IndexMap, items) -> tuple[np.ndarray, np.ndarray]:
    idx, val = [], []
    for it in items:
        i = imap.get_index(it["name"], it.get("term"))
        if i >= 0:
            idx.append(i)
            val.append(it["value"])
    return np.asarray(idx, np.int64), np.asarray(val, np.float64)


def save_game_model(
    model_dir: str,
    model: GameModel,
    index_maps: Mapping[str, IndexMap],
    shard_by_coordinate: Optional[Mapping[str, str]] = None,
    shard_configs: Optional[Mapping[str, object]] = None,
) -> None:
    """Write every coordinate of a GameModel in the reference layout.

    ``shard_configs`` (shard → FeatureShardConfig-like with ``feature_bags``
    and ``add_intercept``) is persisted in the metadata so the scoring driver
    reconstructs the exact feature assembly without re-passing flags.
    """
    os.makedirs(model_dir, exist_ok=True)
    meta: dict = {"coordinates": {}}
    if shard_configs:
        meta["feature_shards"] = {
            shard: {
                "feature_bags": list(cfg.feature_bags),
                "add_intercept": bool(cfg.add_intercept),
            }
            for shard, cfg in shard_configs.items()
        }
    shard_by_coordinate = dict(shard_by_coordinate or {})

    for cid in model.keys():
        m = model[cid]
        factored_extra = None
        if hasattr(m, "effective") and hasattr(m, "projection"):
            # Factored random effect: persist the EFFECTIVE per-entity
            # coefficients in the standard random-effect layout — scoring
            # round-trips through the normal loader, and a factored warm
            # start re-factors them spectrally (the effective matrix is
            # exactly rank-p). projection.npy rides along for inspection.
            factored_extra = np.asarray(m.projection)
            m = m.effective
        if isinstance(m, FixedEffectModel):
            shard = shard_by_coordinate.get(cid, m.feature_shard)
            imap = index_maps[shard]
            cdir = os.path.join(model_dir, "fixed-effect", cid)
            os.makedirs(cdir, exist_ok=True)
            coefs = np.asarray(m.model.coefficients.means)
            nz = np.nonzero(coefs)[0]
            rec = {
                "modelId": cid,
                "modelClass": _MODEL_CLASS[m.model.task],
                "lossFunction": m.model.task.value,
                "means": _nt_list(imap, nz, coefs[nz]),
                "variances": None,
            }
            if m.model.coefficients.variances is not None:
                var = np.asarray(m.model.coefficients.variances)
                # A coefficient can be exactly 0 (e.g. OWL-QN) with a finite
                # posterior variance — keep every nonzero variance entry.
                vnz = np.nonzero(var)[0]
                rec["variances"] = _nt_list(imap, vnz, var[vnz])
            write_container(
                os.path.join(cdir, "coefficients.avro"),
                BAYESIAN_LINEAR_MODEL_AVRO,
                [rec],
            )
            meta["coordinates"][cid] = {
                "type": "fixed",
                "feature_shard": shard,
                "task": m.model.task.value,
            }
        elif isinstance(m, RandomEffectModel):
            shard = shard_by_coordinate.get(cid, "global")
            imap = index_maps[shard]
            cdir = os.path.join(model_dir, "random-effect", cid)
            os.makedirs(cdir, exist_ok=True)

            def entity_records(m=m, imap=imap):
                for key in m.entity_keys:
                    gi, gv, vv = m.export_for(key)
                    yield {
                        "modelId": str(key),
                        "modelClass": _MODEL_CLASS[m.task],
                        "lossFunction": m.task.value,
                        "means": _nt_list(imap, gi, gv),
                        "variances": (
                            _nt_list(imap, gi, vv) if vv is not None else None
                        ),
                    }

            write_container(
                os.path.join(cdir, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_AVRO,
                entity_records(),
            )
            meta["coordinates"][cid] = {
                "type": "random",
                "feature_shard": shard,
                "task": m.task.value,
                "re_type": m.re_type,
            }
            if factored_extra is not None:
                np.save(os.path.join(cdir, "projection.npy"), factored_extra)
                meta["coordinates"][cid]["factored_latent_dim"] = int(
                    factored_extra.shape[1]
                )
        else:
            raise TypeError(f"coordinate {cid}: unknown model type {type(m)}")

    with open(os.path.join(model_dir, _META), "w") as f:
        json.dump(meta, f, indent=2)


def load_game_model(
    model_dir: str, index_maps: Mapping[str, IndexMap], dtype=jnp.float32
) -> tuple[GameModel, dict]:
    """Load a model directory → (GameModel, metadata dict).

    Reference ⟦ModelProcessingUtils.loadGameModelFromHDFS⟧ (SURVEY.md §3.6).
    ``dtype`` sets the in-memory coefficient precision (the Avro layout is
    double either way; pass ``jnp.float64`` under the x64 mode).
    """
    with open(os.path.join(model_dir, _META)) as f:
        meta = json.load(f)
    models: dict = {}
    for cid, info in meta["coordinates"].items():
        imap = index_maps[info["feature_shard"]]
        task = TaskType(info["task"])
        if info["type"] == "fixed":
            recs = read_records(
                os.path.join(model_dir, "fixed-effect", cid, "coefficients.avro")
            )
            if len(recs) != 1:
                raise ValueError(f"{cid}: expected 1 model record, got {len(recs)}")
            gi, gv = _from_nt_list(imap, recs[0]["means"])
            w = np.zeros(len(imap), np.float64)
            w[gi] = gv
            variances = None
            if recs[0].get("variances"):
                vi, vv = _from_nt_list(imap, recs[0]["variances"])
                variances = np.zeros(len(imap), np.float64)
                variances[vi] = vv
                variances = jnp.asarray(variances, dtype)
            glm = GeneralizedLinearModel(
                Coefficients(
                    means=jnp.asarray(w, dtype), variances=variances
                ),
                task,
            )
            models[cid] = FixedEffectModel(glm, info["feature_shard"])
        elif info["type"] == "random":
            cdir = os.path.join(model_dir, "random-effect", cid)
            parts = sorted(
                os.path.join(cdir, p)
                for p in os.listdir(cdir)
                if p.endswith(".avro")
            )
            entity_keys, sparse, sparse_var = [], [], []
            for part in parts:
                for rec in read_records(part):
                    entity_keys.append(rec["modelId"])
                    sparse.append(_from_nt_list(imap, rec["means"]))
                    # null = variances not computed; [] = entity with no
                    # active features (still "has variances" as a coordinate)
                    sparse_var.append(
                        _from_nt_list(imap, rec["variances"])
                        if rec.get("variances") is not None
                        else None
                    )
            if any(v is None for v in sparse_var):
                sparse_var = None
            models[cid] = _synthetic_random_effect_model(
                info.get("re_type", cid), task, entity_keys, sparse, len(imap),
                sparse_var, dtype=dtype,
            )
        else:
            raise ValueError(f"{cid}: unknown coordinate type {info['type']}")
    return GameModel(models), meta


def _synthetic_random_effect_model(
    re_type: str,
    task: TaskType,
    entity_keys: list,
    sparse: list,
    global_dim: int,
    sparse_var: list = None,
    dtype=jnp.float32,
) -> RandomEffectModel:
    """Pack loaded per-entity sparse vectors into SIZE-BUCKETED padded stacks.

    Entities group by the next power of two of their active-feature count, so
    a skewed coordinate (one dense entity among many sparse ones) costs
    O(Σ 2·nnz_e) memory instead of the round-2 loader's O(E × P_max) single
    widest-entity bucket (VERDICT round-2 weak #5 / ask #6).
    """
    if not entity_keys:
        return RandomEffectModel(
            re_type=re_type, task=task,
            bucket_coefs=[jnp.zeros((1, 1), dtype)],
            bucket_proj=[jnp.full((1, 1), global_dim, jnp.int32)],
            bucket_entity_ids=[jnp.zeros((1,), jnp.int32)],
            entity_keys=[], entity_to_slot={}, global_dim=global_dim,
            bucket_variances=(
                [jnp.zeros((1, 1), dtype)] if sparse_var is not None else None
            ),
        )

    def pow2(w: int) -> int:
        return 1 if w <= 1 else 1 << (w - 1).bit_length()

    groups: dict = {}
    for i, (gi, _) in enumerate(sparse):
        groups.setdefault(pow2(len(gi)), []).append(i)

    bucket_coefs, bucket_proj, bucket_ids, bucket_var = [], [], [], []
    entity_to_slot: dict = {}
    for b, (p, members) in enumerate(sorted(groups.items())):
        e = len(members)
        proj = np.full((e, p), global_dim, np.int32)
        coefs = np.zeros((e, p), np.dtype(dtype))
        var = np.zeros((e, p), np.dtype(dtype)) if sparse_var is not None else None
        for slot, i in enumerate(members):
            gi, gv = sparse[i]
            order = np.argsort(gi)  # projection maps sorted by global column
            proj[slot, : len(gi)] = gi[order]
            coefs[slot, : len(gi)] = gv[order]
            if var is not None:
                vi, vv = sparse_var[i]
                # means/variances share the index set on save; align defensively
                vorder = np.argsort(vi)
                if len(vi) != len(gi) or np.any(vi[vorder] != gi[order]):
                    raise ValueError(
                        f"{re_type}: variance indices differ from mean "
                        f"indices for entity {entity_keys[i]!r}"
                    )
                var[slot, : len(vi)] = vv[vorder]
            entity_to_slot[i] = (b, slot)
        bucket_coefs.append(jnp.asarray(coefs))
        bucket_proj.append(jnp.asarray(proj))
        bucket_ids.append(jnp.asarray(members, jnp.int32))
        if var is not None:
            bucket_var.append(jnp.asarray(var))
    return RandomEffectModel(
        re_type=re_type,
        task=task,
        bucket_coefs=bucket_coefs,
        bucket_proj=bucket_proj,
        bucket_entity_ids=bucket_ids,
        entity_keys=list(entity_keys),
        entity_to_slot=entity_to_slot,
        global_dim=global_dim,
        bucket_variances=bucket_var if sparse_var is not None else None,
    )


class ScoresWriter:
    """Streaming ScoringResultAvro writer: append per-chunk score arrays as
    they are computed (chunked scoring never materializes all rows).
    ``save_scores`` is the one-shot form."""

    def __init__(self, path: str):
        from photon_tpu.io.avro import ContainerWriter

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._w = ContainerWriter(path, SCORING_RESULT_AVRO)

    @property
    def n_written(self) -> int:
        return self._w.n_written

    def append(self, scores, uids=None, labels=None) -> None:
        scores = np.asarray(scores, np.float64)
        n = len(scores)
        uids = (
            [None] * n
            if uids is None
            else [None if u is None else str(u) for u in uids]
        )
        labels = (
            [None] * n
            if labels is None
            else [
                None if l is None or l != l  # NaN of any float-like type
                else float(l)
                for l in labels
            ]
        )
        for i in range(n):
            self._w.write({
                "uid": uids[i],
                "predictionScore": float(scores[i]),
                "label": labels[i],
                "metadataMap": None,
            })

    def close(self) -> None:
        self._w.close()

    def abort(self) -> None:
        self._w.abort()

    def __enter__(self) -> "ScoresWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # Unwinding on an exception must not leave a well-formed partial
        # scores file under the final name (see ContainerWriter.abort).
        self._w.__exit__(exc_type, exc, tb)


def save_scores(
    path: str,
    scores,
    uids=None,
    labels=None,
) -> None:
    """Write per-row scores as ScoringResultAvro — reference
    ⟦ScoreProcessingUtils.saveScoresToHDFS⟧."""
    with ScoresWriter(path) as w:
        w.append(scores, uids=uids, labels=labels)


def save_feature_summary(path: str, imap: IndexMap, stats) -> None:
    """Write per-feature summary — reference ⟦FeatureSummarizationResultAvro⟧
    output of the driver's feature-summarization stage."""
    mean = np.asarray(stats.mean)
    var = np.asarray(stats.variance)
    mn = np.asarray(stats.min)
    mx = np.asarray(stats.max)
    nnz = np.asarray(stats.num_nonzeros)

    def recs():
        for i in range(len(mean)):
            name, term = imap.get_feature(i)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(mean[i]),
                    "variance": float(var[i]),
                    "min": float(mn[i]),
                    "max": float(mx[i]),
                    "numNonzeros": float(nnz[i]),
                },
            }

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    write_container(path, FEATURE_SUMMARIZATION_RESULT_AVRO, recs())
