"""I/O layer: Avro codec, data reading, model persistence."""
from photon_tpu.io.avro import (  # noqa: F401
    Decoder,
    Encoder,
    read_container,
    read_records,
    write_container,
)
from photon_tpu.io.data_reader import (  # noqa: F401
    AvroDataReader,
    FeatureShardConfig,
    GameDataBundle,
    InputColumnNames,
    build_index_from_avro,
)
from photon_tpu.io.model_io import (  # noqa: F401
    load_game_model,
    save_feature_summary,
    save_game_model,
    save_scores,
)
