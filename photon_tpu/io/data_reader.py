"""Avro training data → fixed-shape device batches per feature shard.

Parity: reference ⟦photon-client/.../data/avro/AvroDataReader.scala,
DataReader, InputColumnsNames⟧ (SURVEY.md §2.3): read
``TrainingExampleAvro``-shaped records, look every ``(name, term)`` feature up
in the shard's index map, and assemble one sparse feature vector per shard,
carrying response / offset / weight / uid / entity-id columns alongside.

TPU-first: the output is not a DataFrame but a ``GameDataBundle`` — padded
ELL arrays per shard (``ell_from_rows``) in a fixed global row order, plus
host-side numpy id columns. Entity ids for random effects are taken from the
record's ``metadataMap`` (or a top-level field of the same name), exactly the
two places the reference's ``GameConverters`` looks.

Feature bags: a shard assembles from one or more record fields of
``FeatureAvro`` lists (reference: feature-shard-id → feature-bag-keys map),
plus an optional intercept.
"""
from __future__ import annotations

import dataclasses
import glob as globlib
import os
from typing import Iterable, Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import LabeledBatch, SparseFeatures, ell_from_rows
from photon_tpu.index.index_map import (
    INTERCEPT_NAME,
    INTERCEPT_TERM,
    IndexMap,
    build_index_from_features,
)
from photon_tpu.io.avro import read_container


@dataclasses.dataclass(frozen=True)
class InputColumnNames:
    """Reference ⟦InputColumnsNames⟧ defaults."""

    uid: str = "uid"
    response: str = "response"
    offset: str = "offset"
    weight: str = "weight"
    features: str = "features"
    # Reference data often uses "label" instead of "response".
    response_aliases: tuple = ("response", "label")


def response_columns(columns: "InputColumnNames") -> tuple:
    """Label-column lookup order: an explicitly configured response column is
    authoritative; the conventional aliases only apply to the default
    configuration (falling back from a custom name could silently read wrong
    labels). Shared by the per-record and streaming readers so their
    semantics cannot drift."""
    if columns.response in columns.response_aliases:
        return (columns.response,) + tuple(
            a for a in columns.response_aliases if a != columns.response
        )
    return (columns.response,)


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """Which feature bags make up one shard — reference
    ⟦featureShardIdToFeatureSectionKeysMap⟧ + per-shard intercept switch."""

    feature_bags: tuple = ("features",)
    add_intercept: bool = True


@dataclasses.dataclass
class GameDataBundle:
    """All rows of a dataset in one fixed global order.

    ``features[shard]`` are padded ELL arrays; ``id_tags[column]`` are numpy
    string arrays (entity ids for random effects, query ids for grouped
    evaluation — reference GameDatum's idTagToValueMap).
    """

    features: dict
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    uids: np.ndarray
    id_tags: dict

    @property
    def n_rows(self) -> int:
        return len(self.labels)

    def batch(self, shard: str, dtype=None) -> LabeledBatch:
        """``dtype=None`` follows the feature values' dtype, so a bundle read
        with ``dtype=np.float64`` (the x64 mode) trains double end-to-end."""
        feats = self.features[shard]
        if dtype is None:
            dtype = feats.val.dtype
        return LabeledBatch(
            features=feats,
            labels=jnp.asarray(self.labels, dtype),
            offsets=jnp.asarray(self.offsets, dtype),
            weights=jnp.asarray(self.weights, dtype),
        )


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            out.extend(sorted(globlib.glob(os.path.join(p, "*.avro"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no avro files under {paths}")
    return out


class AvroDataReader:
    """Read avro records into a GameDataBundle through per-shard index maps."""

    def __init__(
        self,
        index_maps: Mapping[str, IndexMap],
        shard_configs: Optional[Mapping[str, FeatureShardConfig]] = None,
        columns: InputColumnNames = InputColumnNames(),
        id_tag_columns: Sequence[str] = (),
    ):
        self.index_maps = dict(index_maps)
        self.shard_configs = dict(shard_configs) if shard_configs else {
            s: FeatureShardConfig(feature_bags=(columns.features,))
            for s in self.index_maps
        }
        if set(self.shard_configs) != set(self.index_maps):
            raise ValueError(
                f"shard configs {set(self.shard_configs)} != index maps "
                f"{set(self.index_maps)}"
            )
        self.columns = columns
        self.id_tag_columns = tuple(id_tag_columns)
        self._streaming = None

    def read(
        self, paths, dtype=jnp.float32, require_labels: bool = True,
        capture_uids: bool = True,
    ) -> GameDataBundle:
        """``require_labels=False`` admits unlabeled records (label → NaN) —
        the reference GameScoringDriver treats response as optional at
        scoring time. ``capture_uids=False`` skips materializing the uid
        string column (training never reads it; at 10^8 rows the Python
        string objects would dominate host memory).

        Decoding goes through the streaming block engine
        (``io/streaming.py`` + the native decoder) when the schema supports
        it; otherwise this falls back to the per-record Python path
        (``read_per_record``) with identical semantics.
        """
        from photon_tpu.io.streaming import StreamingAvroReader, Unsupported

        try:
            if self._streaming is None or (
                self._streaming.capture_uids != capture_uids
            ):
                # Cached: the per-shard hash tables and compiled programs are
                # config-determined and reused across read() calls.
                self._streaming = StreamingAvroReader(
                    self.index_maps,
                    self.shard_configs,
                    self.columns,
                    self.id_tag_columns,
                    capture_uids=capture_uids,
                )
            return self._streaming.read(
                paths, dtype=dtype, require_labels=require_labels
            )
        except Unsupported:
            return self.read_per_record(
                paths, dtype, require_labels, capture_uids=capture_uids
            )

    def read_per_record(
        self, paths, dtype=jnp.float32, require_labels: bool = True,
        capture_uids: bool = True,
    ) -> GameDataBundle:
        """Per-record pure-Python decode — the reference implementation the
        streaming engine is tested against, and the fallback for schema
        shapes the program compiler can't express. ``capture_uids=False``
        keeps the uid column empty (same memory contract as the streaming
        reader, so the fallback cannot silently drop it)."""
        cols = self.columns
        labels, offsets, weights, uids = [], [], [], []
        tags: dict[str, list] = {t: [] for t in self.id_tag_columns}
        shard_rows: dict[str, list] = {s: [] for s in self.index_maps}
        response_cols = response_columns(cols)
        # Intercept indices are per-shard invariants; don't look them up per row.
        intercepts = {
            shard: self.index_maps[shard].get_index(INTERCEPT_NAME, INTERCEPT_TERM)
            for shard, cfg in self.shard_configs.items()
            if cfg.add_intercept
        }

        for rec in _iter_records(_expand_paths(paths)):
            lab = _first(rec, response_cols, required=require_labels)
            labels.append(float("nan") if lab is None else lab)
            offsets.append(rec.get(cols.offset) or 0.0)
            w = rec.get(cols.weight)
            weights.append(1.0 if w is None else w)
            if capture_uids:
                uids.append(rec.get(cols.uid) or "")
            meta = rec.get("metadataMap") or {}
            for t in self.id_tag_columns:
                v = rec.get(t)
                if v is None:  # absent OR null top-level field → metadataMap
                    v = meta.get(t)
                if v is None:
                    raise ValueError(
                        f"id tag column {t!r} missing from record and metadataMap"
                    )
                tags[t].append(str(v))

            for shard, cfg in self.shard_configs.items():
                imap = self.index_maps[shard]
                idxs, vals = [], []
                if cfg.add_intercept:
                    ii = intercepts[shard]
                    if ii >= 0:
                        idxs.append(ii)
                        vals.append(1.0)
                for bag in cfg.feature_bags:
                    for feat in rec.get(bag) or ():
                        i = imap.get_index(feat["name"], feat.get("term"))
                        if i >= 0:  # unindexed features dropped, as reference
                            idxs.append(i)
                            vals.append(feat["value"])
                shard_rows[shard].append((idxs, vals))

        features = {
            shard: ell_from_rows(rows, dim=len(self.index_maps[shard]), dtype=dtype)
            for shard, rows in shard_rows.items()
        }
        return GameDataBundle(
            features=features,
            labels=np.asarray(labels, np.float64),
            offsets=np.asarray(offsets, np.float64),
            weights=np.asarray(weights, np.float64),
            uids=(np.asarray(uids, object) if capture_uids
                  else np.full(len(labels), "", object)),
            id_tags={t: np.asarray(v, object) for t, v in tags.items()},
        )


def _iter_records(files: list[str]) -> Iterable[dict]:
    from photon_tpu.faults import fault_point

    for path in files:
        # Chaos hook (docs/robustness.md): per-file IO faults on the
        # per-record fallback path (the streaming path injects per block
        # through io/streaming.py and carries its own bounded retry).
        fault_point("io.record_read", path=path)
        _, it = read_container(path)
        yield from it


def _first(rec: dict, names, required: bool = False):
    for n in names:
        v = rec.get(n)
        if v is not None:
            return v
    if required:
        raise ValueError(f"record missing required column (any of {names}): {rec}")
    return None


def build_index_from_avro(
    paths,
    feature_bags: Sequence[str] = ("features",),
    add_intercept: bool = True,
):
    """Scan avro files and index every (name, term) seen — the in-memory core
    of the reference's ⟦FeatureIndexingDriver⟧.

    The scan runs through the native block decoder's collect mode when
    available (index build at ingest throughput — the reference does this as
    a distributed Spark job); the per-record Python scan is the fallback and
    the semantics reference (identical first-seen order, tested)."""
    from photon_tpu.io.streaming import Unsupported, collect_feature_keys

    try:
        keys = collect_feature_keys(
            paths, {"__index__": FeatureShardConfig(tuple(feature_bags))}
        )
        return build_index_from_features(
            keys["__index__"], add_intercept=add_intercept
        )
    except Unsupported:
        pass

    bags = set(feature_bags)

    def pairs():
        for rec in _iter_records(_expand_paths(paths)):
            # Iterate bags in RECORD (schema-field) order, matching the
            # native collect scan, so both paths index in the same
            # first-seen order even with several bags per shard.
            for field, items in rec.items():
                if field in bags:
                    for feat in items or ():
                        yield feat["name"], feat.get("term")

    return build_index_from_features(pairs(), add_intercept=add_intercept)
