"""Intra-host parallel ingest: worker processes decoding file shards.

Parity: the reference decodes Avro splits on every executor CORE in parallel
(spark-avro tasks; SURVEY.md §2.3, §2.6 "host-side pre-sharding of input
files"). Across hosts this rebuild uses one process per host with
``StreamingAvroReader.iter_chunks(file_shard=...)`` (see
``parallel/distributed.py``); THIS module is the within-host analog — a
spawn pool where worker ``w`` of ``n`` block-decodes files ``w::n`` through
the native decoder and ships columnar chunks back, and the parent reassembles
them in file order into the same ``GameDataBundle`` an in-process read
produces (equality-tested).

Design constraints that shape the code:

* Workers must NEVER touch an accelerator backend — on this machine the TPU
  is a single-client tunnel and a worker claiming it would wedge the chip
  for everyone (memory: axon-tpu-tunnel-wedge). Workers pin the CPU platform
  defensively and only ever build NumPy-backed chunks (the streaming decoder
  path never calls ``jnp.asarray``).
* Everything crossing the process boundary must pickle: index maps travel as
  specs (key lists, or the mmap store's directory), chunks as plain
  numpy-dict payloads with dictionary columns materialized.
* Chunks are tagged (file_position, sequence) so reassembly preserves the
  exact global row order of a sequential read regardless of worker timing.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Optional, Sequence

import numpy as np

from photon_tpu.index.index_map import (
    DefaultIndexMap,
    IndexMap,
    MmapIndexMap,
    feature_key,
)

__all__ = ["read_parallel", "iter_chunks_parallel"]


def _index_spec(im: IndexMap):
    if isinstance(im, MmapIndexMap):
        from photon_tpu.io.streaming import Unsupported

        # Workers reopen the store by path (spawn = same filesystem); a
        # missing directory must surface as Unsupported HERE, before a pool
        # spawns, so the caller's in-process fallback triggers cleanly.
        if not os.path.isdir(im.store_dir):
            raise Unsupported(
                f"mmap index store not a directory: {im.store_dir!r}"
            )
        return ("mmap", im.store_dir)
    try:
        return ("keys", list(im.keys_in_order))
    except AttributeError:
        # feature_key keeps the delimiter for empty terms (the intercept's
        # key is "(INTERCEPT)\x01") so worker-side lookups stay exact.
        return ("keys", [
            feature_key(*im.get_feature(i)) for i in range(len(im))
        ])


def _index_from_spec(spec) -> IndexMap:
    kind, payload = spec
    if kind == "mmap":
        if not os.path.isdir(payload):
            raise FileNotFoundError(
                f"mmap index store {payload!r} not visible in worker "
                "process (store must live on a filesystem shared with the "
                "driver)"
            )
        return MmapIndexMap(payload)
    return DefaultIndexMap(payload)


@dataclasses.dataclass
class _WorkerConfig:
    """Picklable reader construction recipe."""

    index_specs: dict
    shard_configs: dict
    columns: object
    id_tag_columns: tuple
    chunk_rows: int
    capture_uids: bool
    dtype: str
    require_labels: bool


def _chunk_payload(chunk, capture_uids: bool) -> dict:
    """GameDataChunk -> picklable numpy dict (dictionaries materialized).
    With ``capture_uids=False`` the uid column is all defaults — ship None
    instead of n_rows empty-string objects."""
    return {
        "n": chunk.n_rows,
        "labels": chunk.labels,
        "offsets": chunk.offsets,
        "weights": chunk.weights,
        "uids": chunk.uids.materialize("") if capture_uids else None,
        "id_tags": {t: c.materialize() for t, c in chunk.id_tags.items()},
        "features": {
            s: (np.asarray(sf.idx), np.asarray(sf.val), sf.dim)
            for s, sf in chunk.features.items()
        },
    }


def _payload_chunk(payload: dict):
    from photon_tpu.data.batch import SparseFeatures
    from photon_tpu.io.streaming import DictColumn, GameDataChunk

    def col(values):
        return DictColumn(np.arange(len(values), dtype=np.int32), values)

    uids = payload["uids"]
    if uids is None:  # capture_uids=False: all-default column
        uids = DictColumn(
            np.full(payload["n"], -1, np.int32), np.zeros(0, object)
        )
    else:
        uids = col(uids)
    return GameDataChunk(
        labels=payload["labels"],
        offsets=payload["offsets"],
        weights=payload["weights"],
        uids=uids,
        id_tags={t: col(v) for t, v in payload["id_tags"].items()},
        features={
            s: SparseFeatures(idx=i, val=v, dim=d)
            for s, (i, v, d) in payload["features"].items()
        },
    )


# One reader per worker process, built lazily on the first job (spawn pools
# reuse workers across jobs, so the per-process hash tables amortize).
_WORKER_READER = None


def _worker_file(args) -> tuple:
    """Decode ONE file; returns (file_pos, [payload, ...]). Per-file jobs
    bound worker memory to a single file's chunks and let results stream
    back to the parent as each file completes."""
    global _WORKER_READER
    cfg, pos, path = args
    if _WORKER_READER is None:
        # Defensive: a worker must never initialize an accelerator client
        # (the single-client TPU tunnel would wedge); the decode path is
        # numpy-only but pin the platform in case anything touches jax.
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        from photon_tpu.io.streaming import StreamingAvroReader

        _WORKER_READER = StreamingAvroReader(
            {s: _index_from_spec(sp) for s, sp in cfg.index_specs.items()},
            cfg.shard_configs,
            cfg.columns,
            cfg.id_tag_columns,
            chunk_rows=cfg.chunk_rows,
            capture_uids=cfg.capture_uids,
        )
    payloads = [
        _chunk_payload(chunk, cfg.capture_uids)
        for chunk in _WORKER_READER.iter_chunks(
            [path], dtype=np.dtype(cfg.dtype),
            require_labels=cfg.require_labels,
        )
    ]
    return pos, payloads


def iter_chunks_parallel(
    paths,
    index_maps: Mapping[str, IndexMap],
    shard_configs: Mapping[str, object],
    columns=None,
    id_tag_columns: Sequence[str] = (),
    n_workers: int = 0,
    chunk_rows: int = 1 << 20,
    capture_uids: bool = True,
    dtype=np.float32,
    require_labels: bool = True,
):
    """Stream ``GameDataChunk``s decoded by ``n_workers`` processes, in the
    exact global order of a sequential read.

    The worker-pool analog of ``StreamingAvroReader.iter_chunks`` — the feed
    stage ``io/prefetch.py`` builds on: the ORDERED ``imap`` keeps per-file
    results arriving in submission (= file) order while the pool decodes up
    to ``n_workers`` files ahead, so the consumer overlaps whatever it does
    per chunk with the remaining decode. A worker crash (pool teardown,
    corrupt file) surfaces at the consumer's next pull — fast-fail, never a
    hang — and abandoning the generator terminates the pool. Falls back to
    the in-process reader for ``n_workers <= 1``; raises ``Unsupported``
    when the native decoder is unavailable, like the sequential path.
    """
    from photon_tpu import native
    from photon_tpu.io.data_reader import InputColumnNames, _expand_paths
    from photon_tpu.io.streaming import StreamingAvroReader, Unsupported

    if native.get_lib() is None:
        raise Unsupported("native decoder unavailable")
    columns = columns or InputColumnNames()
    files = _expand_paths(paths)
    n_workers = min(int(n_workers), len(files))
    if n_workers <= 1:
        yield from StreamingAvroReader(
            index_maps, shard_configs, columns, id_tag_columns,
            chunk_rows=chunk_rows, capture_uids=capture_uids,
        ).iter_chunks(files, dtype=dtype, require_labels=require_labels)
        return

    cfg = _WorkerConfig(
        index_specs={s: _index_spec(m) for s, m in index_maps.items()},
        shard_configs=dict(shard_configs),
        columns=columns,
        id_tag_columns=tuple(id_tag_columns),
        chunk_rows=chunk_rows,
        capture_uids=capture_uids,
        dtype=np.dtype(dtype).name,
        require_labels=require_labels,
    )
    jobs = iter((cfg, pos, f) for pos, f in enumerate(files))
    import collections
    import concurrent.futures as cf
    import itertools
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    # ProcessPoolExecutor, NOT mp.Pool: an abruptly-dead worker (OOM kill,
    # SIGKILL) raises BrokenProcessPool at result() — mp.Pool silently
    # replaces the worker, loses the job, and a .get() on it hangs forever,
    # which would wedge the training driver's default ingest.
    with cf.ProcessPoolExecutor(max_workers=n_workers,
                                mp_context=ctx) as pool:
        try:
            # Bounded submission window, not submit-everything: a slow
            # streaming consumer must bound parent-side buffering to
            # ~n_workers+1 files' payloads, never accumulate the whole
            # decoded dataset (the constant-memory contract this iterator
            # exists for). Results are consumed in submission (= file =
            # global row) order; worker exceptions AND worker death
            # surface at result() — fast-fail, never a hang.
            pending: collections.deque = collections.deque(
                pool.submit(_worker_file, job)
                for job in itertools.islice(jobs, n_workers + 1)
            )
            while pending:
                _pos, payloads = pending.popleft().result()
                nxt = next(jobs, None)
                if nxt is not None:
                    pending.append(pool.submit(_worker_file, nxt))
                for p in payloads:
                    yield _payload_chunk(p)
        except BaseException:
            # Worker failure OR abandoned consumer: drop queued work so the
            # with-exit's shutdown(wait=True) only drains files already
            # RUNNING — without this a corrupt file's error would sit
            # behind minutes of pointless decode of every queued file.
            for fut in pending:
                fut.cancel()
            raise


def read_parallel(
    paths,
    index_maps: Mapping[str, IndexMap],
    shard_configs: Mapping[str, object],
    columns=None,
    id_tag_columns: Sequence[str] = (),
    n_workers: int = 0,
    chunk_rows: int = 1 << 20,
    capture_uids: bool = True,
    dtype=np.float32,
    require_labels: bool = True,
):
    """Read a multi-file Avro dataset with ``n_workers`` decode processes.

    Returns the same ``GameDataBundle`` (same rows, same order) as
    ``StreamingAvroReader.read`` — workers are a throughput detail, not a
    semantics change. ``n_workers <= 1`` stays in-process. Raises
    ``Unsupported`` (like the streaming reader) when the native decoder or
    schema dialect is unavailable.
    """
    from photon_tpu import native
    from photon_tpu.io.data_reader import InputColumnNames, _expand_paths
    from photon_tpu.io.streaming import (
        StreamingAvroReader,
        Unsupported,
        chunks_to_bundle,
    )

    if native.get_lib() is None:
        # Fail BEFORE spawning a pool: every worker would only start a full
        # interpreter to discover the same thing.
        raise Unsupported("native decoder unavailable")
    columns = columns or InputColumnNames()
    files = _expand_paths(paths)
    if int(n_workers) > len(files) > 0:
        import logging

        logging.getLogger("photon_tpu.io").warning(
            "parallel ingest: %d workers requested but only %d input "
            "file(s) — parallelism is per-file (split the input, or accept "
            "%d-way decode)", n_workers, len(files), len(files),
        )
    n_workers = min(int(n_workers), len(files))
    if n_workers <= 1:
        return StreamingAvroReader(
            index_maps, shard_configs, columns, id_tag_columns,
            chunk_rows=chunk_rows, capture_uids=capture_uids,
        ).read(files, dtype=dtype, require_labels=require_labels)

    cfg = _WorkerConfig(
        index_specs={s: _index_spec(m) for s, m in index_maps.items()},
        shard_configs=dict(shard_configs),
        columns=columns,
        id_tag_columns=tuple(id_tag_columns),
        chunk_rows=chunk_rows,
        capture_uids=capture_uids,
        dtype=np.dtype(dtype).name,
        require_labels=require_labels,
    )
    jobs = [(cfg, pos, f) for pos, f in enumerate(files)]
    # spawn, not fork: fork after JAX initialization can deadlock. Per-file
    # jobs + imap_unordered stream results back as each file finishes, so a
    # worker holds at most one file's chunks and peak memory stays ~1x the
    # dataset (the parent's reassembly) instead of 2x.
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    with ctx.Pool(n_workers) as pool:
        by_pos = dict(pool.imap_unordered(_worker_file, jobs))
    chunks = [
        _payload_chunk(p) for pos in range(len(files)) for p in by_pos[pos]
    ]
    return chunks_to_bundle(chunks, index_maps, id_tag_columns, dtype)
