"""The photon Avro schemas, as parsed-JSON schema objects.

Parity: reference ⟦photon-avro-schemas/src/main/avro/⟧ (SURVEY.md §2.4):
``TrainingExampleAvro`` (label, optional weight/offset, features as a list of
name/term/value triples, metadata map), ``FeatureAvro``/``NameTermValueAvro``,
``BayesianLinearModelAvro`` (means + optional variances as name/term/value
lists, model class, loss function), ``FeatureSummarizationResultAvro``, and
``ScoringResultAvro`` — byte-compatible with files the reference reads and
writes, so a user can point this framework at existing photon-ml datasets and
model directories.
"""
from __future__ import annotations

NAMESPACE = "com.linkedin.photon.avro.generated"

NAME_TERM_VALUE_AVRO = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": ["null", "string"], "default": None},
        {"name": "value", "type": "double"},
    ],
}

FEATURE_AVRO = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": ["null", "string"], "default": None},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
        {
            "name": "means",
            "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO},
        },
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
    ],
}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": ["null", "string"], "default": None},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

SCORING_RESULT_AVRO = {
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {
            "name": "metadataMap",
            "type": ["null", {"type": "map", "values": "string"}],
            "default": None,
        },
    ],
}
