"""Pipelined ingest→device data path: prefetched decode + double-buffered
host→device transfer.

Why: BENCH_r05 put ``fraction_of_roofline`` ≈ 0.15 and the PR 6 timeline
analyzer's overlap verdict on the smoke bench at ``serialized`` (0.0) —
after the PR 4 Newton work the optimizers are no longer the bottleneck,
feeding them is. Upstream photon-ml never paid this cost: spark-avro block
decode runs inside the executor pipeline, concurrently with the
``treeAggregate`` passes (PAPER.md survey). photon-tpu decoded blocks,
uploaded, and computed strictly in sequence. This module is the pipeline:

* :func:`prefetch` — a bounded background stage running any chunk iterator
  (``StreamingAvroReader.iter_chunks``, or the ``parallel_ingest`` worker
  pool via :func:`iter_chunks_pipelined`) on a producer thread, so block
  decode of chunk *N+1* overlaps whatever the consumer does with chunk *N*.
  The native decoder releases the GIL inside ``ph_decode_block``, so the
  overlap is real even single-process. Queue depth bounds host memory
  (``depth`` × chunk size); the consumer's blocking get is traced as an
  ``ingest.prefetch_queue_wait`` span (the analyzer's ``*queue_wait*``
  breakdown picks it up), and the producer loop carries an ``io.prefetch``
  fault point so the chaos suite can kill the stage mid-stream.
* :func:`pipelined_puts` — double-buffered ``device_put``: the transfer for
  item *N+1* is issued before item *N* is yielded to the consumer, so on an
  accelerator backend H2D DMA for the next chunk runs while the current
  chunk computes. ``donate=True`` is requested where the runtime supports
  it so the staging buffer's pages move instead of copying.
* :func:`device_put_chunk` / :func:`read_bundle_pipelined` — the composed
  path from Avro files to device-backed chunks / a ``GameDataBundle``,
  with an opt-in **bf16 feed** (``feed_dtype``): feature values are
  narrowed to bfloat16 ON THE HOST (``ml_dtypes``) before ``device_put``,
  halving transfer bytes on the hot path, while every consumer kernel
  accumulates in f32 via dtype promotion (``SparseFeatures.matvec``
  multiplies bf16 values against an f32 coefficient gather — tolerance-
  gated in tests/test_prefetch.py like the PR 1 dtype work).

The multi-sweep device-residency half of the data path (pin the dataset on
device after sweep 0) lives in ``photon_tpu/data/device_cache.py``; the
out-of-core solver threads both through its streamed passes
(``optim/out_of_core.py``).
"""
from __future__ import annotations

import collections
import os
import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from photon_tpu.faults import fault_point
from photon_tpu.obs import trace_span
from photon_tpu.obs.metrics import REGISTRY

__all__ = [
    "default_prefetch_depth",
    "prefetch",
    "pipelined_puts",
    "device_put_chunk",
    "iter_chunks_pipelined",
    "read_bundle_pipelined",
    "host_feed_array",
]

_PREFETCHED_CHUNKS = REGISTRY.counter(
    "ingest_prefetch_chunks_total",
    "Chunks decoded ahead by the ingest prefetch stage",
)
_FEED_BYTES = REGISTRY.counter(
    "ingest_device_put_bytes_total",
    "Bytes shipped host->device by the pipelined ingest feed",
)


def default_prefetch_depth() -> int:
    """Queue bound for the background decode stage (``PHOTON_PREFETCH_DEPTH``;
    0 disables prefetching entirely)."""
    try:
        return max(0, int(os.environ.get("PHOTON_PREFETCH_DEPTH", "2")))
    except ValueError:
        return 2


def prefetch(iterable: Iterable, depth: Optional[int] = None) -> Iterator:
    """Yield from ``iterable`` while a background thread runs it ``depth``
    items ahead.

    Exceptions from the producer (including an ``OSError`` that outlived
    ``io_retries`` inside ``iter_blocks_with_retry``) re-raise at the
    consumer's next pull, in order — a failing stream fails the pipeline,
    never hangs it. Abandoning the generator (``close()`` / GC) stops the
    producer promptly: it checks a stop flag around every bounded put.

    ``depth <= 0`` degrades to plain iteration (no thread) so callers can
    thread one knob through unconditionally.
    """
    if depth is None:
        depth = default_prefetch_depth()
    if depth <= 0:
        yield from iterable
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    END = object()

    def produce() -> None:
        try:
            n = 0
            for item in iterable:
                fault_point("io.prefetch", item=n)
                n += 1
                _PREFETCHED_CHUNKS.inc()
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            _put_end(None)
        except BaseException as e:  # noqa: BLE001 - relayed to the consumer
            _put_end(e)

    def _put_end(err) -> None:
        while not stop.is_set():
            try:
                q.put((END, err), timeout=0.1)
                return
            except queue.Full:
                continue

    t = threading.Thread(target=produce, name="photon-prefetch", daemon=True)
    t.start()
    try:
        while True:
            with trace_span("ingest.prefetch_queue_wait", cat="ingest"):
                item = q.get()
            if isinstance(item, tuple) and len(item) == 2 and item[0] is END:
                if item[1] is not None:
                    raise item[1]
                return
            yield item
    finally:
        stop.set()
        # Drain so a producer blocked on a full queue can observe the stop
        # flag and exit before the (bounded) join.
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)


def pipelined_puts(items: Iterable, put: Callable, ahead: int = 1) -> Iterator:
    """Apply ``put`` (typically a ``device_put`` wrapper) to each item,
    keeping ``ahead`` results in flight: the transfer for item N+1 is issued
    before item N is yielded, so async backends overlap the next chunk's H2D
    DMA with the current chunk's compute (double buffer at ``ahead=1``)."""
    pending: collections.deque = collections.deque()
    for item in items:
        pending.append(put(item))
        while len(pending) > max(ahead, 0):
            yield pending.popleft()
    while pending:
        yield pending.popleft()


def host_feed_array(a: np.ndarray, feed_dtype=None) -> np.ndarray:
    """Narrow a host value array to the feed dtype ON THE HOST (so the wire
    transfer itself shrinks — casting after ``device_put`` would ship f32).
    ``ml_dtypes`` supplies the numpy bfloat16; identity when ``feed_dtype``
    is None or already matches."""
    if feed_dtype is None:
        return a
    import ml_dtypes  # ships with jax

    dt = np.dtype(feed_dtype) if not isinstance(feed_dtype, str) else None
    if dt is None:
        dt = np.dtype(
            ml_dtypes.bfloat16 if feed_dtype == "bfloat16" else feed_dtype
        )
    if a.dtype == dt:
        return a
    return a.astype(dt)


def _device_put(x, donate: bool = True):
    """``jax.device_put`` requesting input-buffer donation where the runtime
    accepts it (a donated staging buffer moves instead of copying; numpy
    inputs that cannot donate fall back to the plain copy path)."""
    import jax

    if donate:
        try:
            return jax.device_put(x, donate=True)
        except (TypeError, ValueError):
            pass
    return jax.device_put(x)


def device_put_chunk(chunk, feed_dtype=None, donate: bool = True):
    """One streamed ``GameDataChunk``, numeric payload moved to device.

    Features (ELL idx/val), labels, offsets, and weights become device
    arrays; uid/tag dictionary columns stay host (they are never device
    operands). ``feed_dtype`` narrows the feature VALUES on the host first
    (bf16 feed). The whole transfer is one ``ingest.device_put`` span so
    the timeline analyzer sees the feed as ingest work.
    """
    import jax.numpy as jnp

    from photon_tpu.data.batch import SparseFeatures
    from photon_tpu.io.streaming import GameDataChunk

    with trace_span("ingest.device_put", cat="ingest",
                    rows=chunk.n_rows) as sp:
        features = {}
        for s, sf in chunk.features.items():
            val = host_feed_array(np.asarray(sf.val), feed_dtype)
            features[s] = SparseFeatures(
                idx=_device_put(np.asarray(sf.idx), donate=False),  # shared
                val=_device_put(val, donate=donate and val is not sf.val),
                dim=sf.dim,
            )
        out = GameDataChunk(
            labels=jnp.asarray(chunk.labels),
            offsets=jnp.asarray(chunk.offsets),
            weights=jnp.asarray(chunk.weights),
            uids=chunk.uids,
            id_tags=chunk.id_tags,
            features=features,
        )
        # Bytes from the PRODUCED device arrays, not the host inputs: the
        # runtime narrows f64 row columns to f32 (x64 off) and the bf16
        # feed halves values — the tracked ingest_to_device figure must
        # report what actually moved, not the host-side staging size.
        nbytes = out.labels.nbytes + out.offsets.nbytes + out.weights.nbytes
        for sf in out.features.values():
            nbytes += sf.idx.nbytes + sf.val.nbytes
        sp.set(bytes=int(nbytes))
    _FEED_BYTES.inc(int(nbytes))
    return out


def iter_chunks_pipelined(
    reader,
    paths,
    dtype=np.float32,
    require_labels: bool = True,
    depth: Optional[int] = None,
    workers: int = 0,
    to_device: bool = False,
    feed_dtype=None,
) -> Iterator:
    """``StreamingAvroReader.iter_chunks`` behind the prefetch stage.

    ``workers > 1`` decodes file shards on the ``parallel_ingest`` worker
    pool (chunks stream back in exact file order) instead of in-process;
    ``to_device=True`` additionally runs the double-buffered device feed so
    the yielded chunks carry device arrays (chunk *N+1* decodes and uploads
    while chunk *N* computes).
    """
    if workers and workers > 1:
        from photon_tpu.io.parallel_ingest import iter_chunks_parallel

        src = iter_chunks_parallel(
            paths,
            reader.index_maps,
            reader.shard_configs,
            reader.columns,
            reader.id_tag_columns,
            n_workers=workers,
            chunk_rows=reader.chunk_rows,
            capture_uids=reader.capture_uids,
            dtype=dtype,
            require_labels=require_labels,
        )
    else:
        src = reader.iter_chunks(paths, dtype=dtype,
                                 require_labels=require_labels)
    out = prefetch(src, depth=depth)
    if to_device:
        out = pipelined_puts(
            out, lambda c: device_put_chunk(c, feed_dtype=feed_dtype),
            ahead=1,
        )
    return out


def read_bundle_pipelined(
    index_maps,
    shard_configs,
    columns,
    id_tag_columns,
    paths,
    dtype=np.float32,
    require_labels: bool = True,
    capture_uids: bool = False,
    depth: Optional[int] = None,
    workers: int = 0,
    feed_dtype=None,
    chunk_rows: int = 1 << 20,
    io_retries: int = 2,
    reader=None,
):
    """Full-dataset read through the prefetched decode stage: block decode
    of chunk N+1 runs on the producer thread while the consumer assembles
    chunk N into the bundle. Same rows, same order, bit-identical to a
    sequential ``StreamingAvroReader.read`` (tested); raises
    ``io.streaming.Unsupported`` exactly when the sequential path would, so
    callers keep their per-record fallback.

    Pass a ``reader`` (``StreamingAvroReader``) to reuse its compiled decode
    programs and per-shard hash tables across calls (a train+validation run
    must not build the 100K+-feature probe tables twice); when given, it
    overrides the construction args."""
    from photon_tpu.io.streaming import StreamingAvroReader, chunks_to_bundle

    if reader is None:
        reader = StreamingAvroReader(
            index_maps, shard_configs, columns, id_tag_columns,
            chunk_rows=chunk_rows, capture_uids=capture_uids,
            io_retries=io_retries,
        )
    chunks = list(iter_chunks_pipelined(
        reader, paths, dtype=dtype, require_labels=require_labels,
        depth=depth, workers=workers,
    ))
    return chunks_to_bundle(
        chunks, index_maps, id_tag_columns, dtype, feed_dtype=feed_dtype,
    )
