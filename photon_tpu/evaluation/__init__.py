"""Evaluation: metric functions + evaluator/suite API (SURVEY.md §2.2)."""
from photon_tpu.evaluation.evaluator import (  # noqa: F401
    EvaluationResults,
    EvaluationSuite,
    Evaluator,
    parse_evaluator,
)
from photon_tpu.evaluation.metrics import (  # noqa: F401
    auc,
    grouped_auc,
    grouped_precision_at_k,
    logistic_loss,
    poisson_loss,
    rmse,
    smoothed_hinge_loss,
    squared_loss,
)
