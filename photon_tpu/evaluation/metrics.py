"""Pure metric functions over score/label/weight arrays.

Parity: reference ⟦photon-api/.../evaluation/⟧ — `AreaUnderROCCurveEvaluator`,
`RMSEEvaluator`, `PoissonLossEvaluator`, `SquaredLossEvaluator`,
`LogisticLossEvaluator`, `SmoothedHingeLossEvaluator`, `PrecisionAtKEvaluator`
and the sharded/grouped `MultiEvaluator` variants (SURVEY.md §2.2).

TPU-first: every metric is a fixed-shape jit-compatible function of
``(scores, labels, weights[, group_ids])``. Weight 0 marks padding, so the
same functions work on padded/sharded batches. Grouped metrics use
``segment_sum`` over dense group ids instead of the reference's
RDD ``groupBy`` — one pass, no shuffle (SURVEY.md §2.6 table).

AUC uses the weighted Mann-Whitney statistic with half-credit for score ties
(equal to trapezoidal ROC integration, the reference's tie convention —
SURVEY.md §7 hard-part #7).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-12


def _tie_group_ids(sorted_scores: Array) -> Array:
    """Dense ids of equal-score runs in an already-sorted score vector."""
    n = sorted_scores.shape[0]
    boundary = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         (sorted_scores[1:] != sorted_scores[:-1]).astype(jnp.int32)]
    )
    return jnp.cumsum(boundary)


def auc(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted ROC AUC with average-rank (trapezoidal) tie handling.

    AUC = Σ_pos w⁺·(W⁻_below + ½·W⁻_tied) / (W⁺·W⁻). Returns NaN when either
    class has zero total weight (undefined, as in the reference).
    """
    w = jnp.ones_like(scores) if weights is None else weights
    order = jnp.argsort(scores)
    s, y, w = scores[order], labels[order], w[order]
    pos_w = w * (y > 0.5)
    neg_w = w * (y <= 0.5)

    g = _tie_group_ids(s)
    n = s.shape[0]
    neg_per_group = jax.ops.segment_sum(neg_w, g, num_segments=n)
    neg_below = jnp.cumsum(neg_per_group) - neg_per_group  # exclusive prefix

    credit = pos_w * (neg_below[g] + 0.5 * neg_per_group[g])
    w_pos = jnp.sum(pos_w)
    w_neg = jnp.sum(neg_w)
    return jnp.where(
        (w_pos > 0) & (w_neg > 0),
        jnp.sum(credit) / jnp.maximum(w_pos * w_neg, _EPS),
        jnp.nan,
    )


def rmse(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    w = jnp.ones_like(scores) if weights is None else weights
    se = w * (scores - labels) ** 2
    return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(w), _EPS))


def squared_loss(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted mean squared error (reference SquaredLossEvaluator is a sum;
    we report the weighted mean so values are comparable across data sizes,
    matching how the reference normalizes in its sharded variants)."""
    w = jnp.ones_like(scores) if weights is None else weights
    return jnp.sum(w * (scores - labels) ** 2) / jnp.maximum(jnp.sum(w), _EPS)


def logistic_loss(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted mean logistic negative log-likelihood of raw scores."""
    w = jnp.ones_like(scores) if weights is None else weights
    # log(1+e^z) - y z, stable via logaddexp.
    ll = jnp.logaddexp(0.0, scores) - labels * scores
    return jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), _EPS)


def poisson_loss(scores: Array, labels: Array, weights: Array | None = None) -> Array:
    """Weighted mean Poisson negative log-likelihood (dropping log y! const)."""
    w = jnp.ones_like(scores) if weights is None else weights
    nll = jnp.exp(scores) - labels * scores
    return jnp.sum(w * nll) / jnp.maximum(jnp.sum(w), _EPS)


def smoothed_hinge_loss(
    scores: Array, labels: Array, weights: Array | None = None
) -> Array:
    """Weighted mean Rennie smoothed hinge on ±1 targets (0/1 labels accepted)."""
    w = jnp.ones_like(scores) if weights is None else weights
    t = jnp.where(labels > 0.5, 1.0, -1.0)
    z = t * scores
    loss = jnp.where(
        z >= 1.0, 0.0, jnp.where(z <= 0.0, 0.5 - z, 0.5 * (1.0 - z) ** 2)
    )
    return jnp.sum(w * loss) / jnp.maximum(jnp.sum(w), _EPS)


# -- grouped ("sharded"/Multi) metrics --------------------------------------


def _group_sort(group_ids: Array, scores: Array):
    """Sort rows by (group, score desc); returns permutation."""
    # Two stable sorts: by -score, then by group — lexicographic.
    order1 = jnp.argsort(-scores, stable=True)
    order2 = jnp.argsort(group_ids[order1], stable=True)
    return order1[order2]


def grouped_auc(
    scores: Array,
    labels: Array,
    group_ids: Array,
    weights: Array | None = None,
    num_groups: int | None = None,
) -> Array:
    """Unweighted-mean over groups of within-group AUC.

    Reference ⟦MultiAUCEvaluator / ShardedAUC:idTag⟧: groups lacking both a
    positive and a negative are skipped. ``group_ids`` are dense ints in
    [0, num_groups).
    """
    w = jnp.ones_like(scores) if weights is None else weights
    m = num_groups if num_groups is not None else scores.shape[0]
    order = _group_sort(group_ids, -scores)  # ascending score within group
    gsort, ssort, ysort, wsort = (
        group_ids[order], scores[order], labels[order], w[order]
    )
    n = scores.shape[0]

    # Tie runs within (group, score): break runs when either changes.
    boundary = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         ((ssort[1:] != ssort[:-1]) | (gsort[1:] != gsort[:-1])).astype(jnp.int32)]
    )
    tie = jnp.cumsum(boundary)

    pos_w = wsort * (ysort > 0.5)
    neg_w = wsort * (ysort <= 0.5)
    neg_per_tie = jax.ops.segment_sum(neg_w, tie, num_segments=n)
    neg_cum_incl = jnp.cumsum(neg_per_tie)  # over tie groups

    # Exclusive prefix of negatives *within this group*: subtract the value at
    # the group's first tie run.
    first_tie_of_group = jax.ops.segment_min(tie, gsort, num_segments=m)
    neg_before_tie = neg_cum_incl - neg_per_tie            # exclusive, global
    group_base = neg_before_tie[first_tie_of_group]         # [m]
    neg_below_in_group = neg_before_tie[tie] - group_base[gsort]

    credit = pos_w * (neg_below_in_group + 0.5 * neg_per_tie[tie])
    auc_num = jax.ops.segment_sum(credit, gsort, num_segments=m)
    w_pos = jax.ops.segment_sum(pos_w, gsort, num_segments=m)
    w_neg = jax.ops.segment_sum(neg_w, gsort, num_segments=m)
    valid = (w_pos > 0) & (w_neg > 0)
    per_group = auc_num / jnp.maximum(w_pos * w_neg, _EPS)
    n_valid = jnp.sum(valid)
    return jnp.where(
        n_valid > 0,
        jnp.sum(jnp.where(valid, per_group, 0.0)) / jnp.maximum(n_valid, 1),
        jnp.nan,
    )


def grouped_precision_at_k(
    scores: Array,
    labels: Array,
    group_ids: Array,
    k: int,
    weights: Array | None = None,
    num_groups: int | None = None,
) -> Array:
    """Mean over groups of (# positives in the group's top-k scores) / k.

    Reference ⟦PrecisionAtKEvaluator⟧ divides by k (not group size); groups
    with no valid rows are skipped. Rows with weight 0 (padding) are ignored.
    """
    w = jnp.ones_like(scores) if weights is None else weights
    m = num_groups if num_groups is not None else scores.shape[0]
    valid_row = w > 0
    # Push invalid rows to the bottom by group-sorting on masked scores.
    neg_inf = jnp.asarray(-jnp.inf, scores.dtype)
    masked = jnp.where(valid_row, scores, neg_inf)
    order = _group_sort(group_ids, masked)
    gsort = group_ids[order]
    ysort = (labels[order] > 0.5) & valid_row[order]

    # Rank within group = position - group start.
    n = scores.shape[0]
    pos_idx = jnp.arange(n)
    group_start = jax.ops.segment_min(pos_idx, gsort, num_segments=m)
    rank = pos_idx - group_start[gsort]
    in_top_k = (rank < k) & valid_row[order]

    hits = jax.ops.segment_sum(
        (ysort & in_top_k).astype(scores.dtype), gsort, num_segments=m
    )
    group_rows = jax.ops.segment_sum(
        valid_row[order].astype(scores.dtype), gsort, num_segments=m
    )
    has_rows = group_rows > 0
    per_group = hits / k
    n_valid = jnp.sum(has_rows)
    return jnp.where(
        n_valid > 0,
        jnp.sum(jnp.where(has_rows, per_group, 0.0)) / jnp.maximum(n_valid, 1),
        jnp.nan,
    )


def _per_row_loss(kind: str, scores: Array, labels: Array) -> Array:
    if kind in ("RMSE", "SQUARED_LOSS"):
        return (scores - labels) ** 2
    if kind == "LOGISTIC_LOSS":
        return jnp.logaddexp(0.0, scores) - labels * scores
    if kind == "POISSON_LOSS":
        return jnp.exp(scores) - labels * scores
    if kind == "SMOOTHED_HINGE_LOSS":
        t = jnp.where(labels > 0.5, 1.0, -1.0)
        z = t * scores
        return jnp.where(
            z >= 1.0, 0.0, jnp.where(z <= 0.0, 0.5 - z, 0.5 * (1.0 - z) ** 2)
        )
    raise ValueError(f"no per-row loss for {kind}")


def grouped_pointwise(
    kind: str,
    scores: Array,
    labels: Array,
    group_ids: Array,
    weights: Array | None = None,
    num_groups: int | None = None,
) -> Array:
    """Generic grouped ("sharded") variant of the pointwise metrics: the
    within-group weighted mean of the per-row loss (root-mean for RMSE), then
    the UNWEIGHTED mean over non-empty groups — the reference
    ⟦MultiEvaluator⟧ convention grouped AUC already follows. NaN when every
    group is empty."""
    w = jnp.ones_like(scores) if weights is None else weights
    m = num_groups if num_groups is not None else scores.shape[0]
    per_row = _per_row_loss(kind, scores, labels)
    num = jax.ops.segment_sum(w * per_row, group_ids, num_segments=m)
    den = jax.ops.segment_sum(w, group_ids, num_segments=m)
    val = num / jnp.maximum(den, _EPS)
    if kind == "RMSE":
        val = jnp.sqrt(val)
    valid = den > 0
    n_valid = jnp.sum(valid)
    return jnp.where(
        n_valid > 0,
        jnp.sum(jnp.where(valid, val, 0.0)) / jnp.maximum(n_valid, 1),
        jnp.nan,
    )
