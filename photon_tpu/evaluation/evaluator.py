"""Evaluator objects, type parsing, and evaluation suites.

Parity: reference ⟦photon-api/.../evaluation/Evaluator.scala, EvaluatorType,
EvaluationSuite, EvaluationResults⟧ (SURVEY.md §2.2): evaluators know their
name and direction (is bigger better), suites bundle several with one primary
metric, and evaluator types parse from strings — "AUC", "RMSE",
"PRECISION@5:queryId", "AUC:queryId" for grouped variants.

The score input is the additive GAME score (raw linear scale); each evaluator
applies whatever link it needs, as in the reference (AUC ranks raw scores,
Poisson loss exponentiates, RMSE compares raw scores for linear regression).
"""
from __future__ import annotations

import dataclasses
import re
from typing import Mapping, Optional, Sequence

import jax
import numpy as np

from photon_tpu.evaluation import metrics

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """A named metric with an ordering. ``group_column`` marks grouped
    ("sharded") variants that need per-row group ids at evaluate time."""

    name: str
    kind: str                      # one of the _KINDS keys
    bigger_is_better: bool
    k: Optional[int] = None        # precision@k only
    group_column: Optional[str] = None

    def evaluate(
        self,
        scores: Array,
        labels: Array,
        weights: Array | None = None,
        group_ids: Array | None = None,
        num_groups: int | None = None,
    ) -> float:
        if self.kind == "AUC":
            v = metrics.auc(scores, labels, weights)
        elif self.kind == "RMSE":
            v = metrics.rmse(scores, labels, weights)
        elif self.kind == "SQUARED_LOSS":
            v = metrics.squared_loss(scores, labels, weights)
        elif self.kind == "LOGISTIC_LOSS":
            v = metrics.logistic_loss(scores, labels, weights)
        elif self.kind == "POISSON_LOSS":
            v = metrics.poisson_loss(scores, labels, weights)
        elif self.kind == "SMOOTHED_HINGE_LOSS":
            v = metrics.smoothed_hinge_loss(scores, labels, weights)
        elif self.kind == "GROUPED_AUC":
            if group_ids is None:
                raise ValueError(f"{self.name} needs group_ids")
            v = metrics.grouped_auc(scores, labels, group_ids, weights, num_groups)
        elif self.kind.startswith("GROUPED_"):
            if group_ids is None:
                raise ValueError(f"{self.name} needs group_ids")
            v = metrics.grouped_pointwise(
                self.kind[len("GROUPED_"):], scores, labels, group_ids,
                weights, num_groups,
            )
        elif self.kind == "PRECISION_AT_K":
            if group_ids is None:
                raise ValueError(f"{self.name} needs group_ids")
            v = metrics.grouped_precision_at_k(
                scores, labels, group_ids, self.k, weights, num_groups
            )
        else:  # pragma: no cover - parse() keeps kinds closed
            raise ValueError(f"unknown evaluator kind {self.kind}")
        return float(v)

    def better_than(self, a: float, b: float) -> bool:
        """Is metric value ``a`` strictly better than ``b`` (NaN never wins)?"""
        if np.isnan(a):
            return False
        if np.isnan(b):
            return True
        return a > b if self.bigger_is_better else a < b


_PRECISION_RE = re.compile(r"^PRECISION@(\d+):(.+)$", re.IGNORECASE)

_SIMPLE_KINDS = {
    "AUC": True,                 # kind -> bigger_is_better
    "RMSE": False,
    "SQUARED_LOSS": False,
    "LOGISTIC_LOSS": False,
    "POISSON_LOSS": False,
    "SMOOTHED_HINGE_LOSS": False,
}


def parse_evaluator(spec: str) -> Evaluator:
    """Parse a reference-style evaluator spec string.

    Forms: "AUC" | "RMSE" | "SQUARED_LOSS" | "LOGISTIC_LOSS" | "POISSON_LOSS"
    | "SMOOTHED_HINGE_LOSS" | "AUC:groupCol" | "PRECISION@k:groupCol".
    """
    s = spec.strip()
    m = _PRECISION_RE.match(s)
    if m:
        k, col = int(m.group(1)), m.group(2)
        return Evaluator(
            name=f"PRECISION@{k}:{col}", kind="PRECISION_AT_K",
            bigger_is_better=True, k=k, group_column=col,
        )
    if ":" in s:
        head, col = s.split(":", 1)
        head = head.strip().upper()
        if head in _SIMPLE_KINDS:
            # Grouped ("sharded"/Multi) family: AUC:col, RMSE:col,
            # LOGISTIC_LOSS:col, ... — reference ⟦MultiEvaluator⟧ by-group
            # averaging for every base metric.
            kind = "GROUPED_AUC" if head == "AUC" else f"GROUPED_{head}"
            return Evaluator(
                name=f"{head}:{col}", kind=kind,
                bigger_is_better=_SIMPLE_KINDS[head], group_column=col,
            )
        raise ValueError(f"unknown grouped evaluator {spec!r}")
    kind = s.upper()
    if kind not in _SIMPLE_KINDS:
        raise ValueError(f"unknown evaluator {spec!r}")
    return Evaluator(name=kind, kind=kind, bigger_is_better=_SIMPLE_KINDS[kind])


@dataclasses.dataclass(frozen=True)
class EvaluationResults:
    """Metric values keyed by evaluator name; first entry of ``suite`` is
    primary (reference ⟦EvaluationResults⟧)."""

    values: Mapping[str, float]
    primary_name: str

    @property
    def primary(self) -> float:
        return self.values[self.primary_name]

    def __repr__(self) -> str:
        vals = ", ".join(f"{k}={v:.6g}" for k, v in self.values.items())
        return f"EvaluationResults({vals}; primary={self.primary_name})"


@dataclasses.dataclass(frozen=True)
class EvaluationSuite:
    """Several evaluators over one validation set; the first is primary."""

    evaluators: Sequence[Evaluator]

    @staticmethod
    def parse(specs: Sequence[str]) -> "EvaluationSuite":
        if not specs:
            raise ValueError("at least one evaluator spec required")
        return EvaluationSuite(tuple(parse_evaluator(s) for s in specs))

    @property
    def primary(self) -> Evaluator:
        return self.evaluators[0]

    def evaluate(
        self,
        scores: Array,
        labels: Array,
        weights: Array | None = None,
        group_ids_by_column: Mapping[str, Array] | None = None,
        num_groups_by_column: Mapping[str, int] | None = None,
    ) -> EvaluationResults:
        values = {}
        for ev in self.evaluators:
            gid = None
            ng = None
            if ev.group_column is not None:
                if not group_ids_by_column or ev.group_column not in group_ids_by_column:
                    raise ValueError(
                        f"evaluator {ev.name} needs group ids for column "
                        f"{ev.group_column!r}"
                    )
                gid = group_ids_by_column[ev.group_column]
                if num_groups_by_column:
                    ng = num_groups_by_column.get(ev.group_column)
            values[ev.name] = ev.evaluate(scores, labels, weights, gid, ng)
        return EvaluationResults(values, self.evaluators[0].name)
