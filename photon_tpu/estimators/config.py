"""Per-coordinate configuration objects for the GAME estimator.

Parity: reference ⟦photon-api/.../optimization/game/
CoordinateOptimizationConfiguration.scala, FixedEffectOptimizationConfiguration,
RandomEffectOptimizationConfiguration, GLMOptimizationConfiguration⟧ and the
per-coordinate dataset configs ⟦FixedEffectDataConfiguration,
RandomEffectDataConfiguration⟧ (SURVEY.md §2.2 "Coordinate configs").

The estimator separates *what data a coordinate trains on* (a data config,
fixed per estimator) from *how it optimizes* (an optimization config, swept
over by ``GameEstimator.fit`` — the reference's multi-reg-weight sweep).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Mapping, Optional, Sequence, Union

from photon_tpu.functions.problem import (
    GLMOptimizationProblem,
    VarianceComputationType,
)
from photon_tpu.optim import OptimizerConfig, OptimizerType
from photon_tpu.optim.regularization import RegularizationContext
from photon_tpu.types import TaskType


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfig:
    """Train one population-level GLM on every row of one feature shard —
    reference ⟦FixedEffectDataConfiguration(featureShardId, minPartitions)⟧
    (partition count is meaningless on a mesh and dropped)."""

    feature_shard: str = "global"


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfig:
    """Per-entity GLMs grouped by an id column — reference
    ⟦RandomEffectDataConfiguration(randomEffectType, featureShardId,
    numActiveDataPointsUpperBound, numActiveDataPointsLowerBound, ...)⟧.

    ``active_bound`` caps rows used for *training* per entity (rows beyond it
    become passive: scored, not trained on); ``min_entity_rows`` drops
    entities with too little data (they fall back to the zero model);
    ``max_features_per_entity`` applies Pearson-correlation feature filtering
    to each entity's local dataset before projection (reference
    ⟦LocalDataset.filterFeaturesByPearsonCorrelationScore⟧).
    """

    re_type: str
    feature_shard: str = "global"
    active_bound: Optional[int] = None
    min_entity_rows: int = 1
    max_features_per_entity: Optional[int] = None
    # Scale controls (no reference equivalent — Spark partitions replace
    # them there): cap entities per bucket, and keep bucket arrays host-
    # resident so the trainer streams ONE bucket at a time through the
    # device (peak HBM = one bucket).
    max_bucket_entities: Optional[int] = None
    host_resident: bool = False


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectDataConfig(RandomEffectDataConfig):
    """Random effects constrained to a learned latent space ``w_e = P·β_e``
    — reference ⟦FactoredRandomEffectDataConfiguration⟧ (fork-vintage; see
    game/factored_random_effect.py). Dataset preparation is identical to a
    plain random effect; training alternates latent/projection steps."""

    latent_dim: int = 8
    n_alternations: int = 2

    def __post_init__(self):
        if self.latent_dim < 1:
            raise ValueError(f"latent_dim must be >= 1, got {self.latent_dim}")
        if self.n_alternations < 1:
            raise ValueError(
                f"n_alternations must be >= 1, got {self.n_alternations}"
            )


CoordinateDataConfig = Union[
    FixedEffectDataConfig, RandomEffectDataConfig, FactoredRandomEffectDataConfig
]


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """One coordinate's optimization recipe — reference
    ⟦GLMOptimizationConfiguration(optimizerConfig, regularizationContext,
    regularizationWeight, downSamplingRate)⟧ + variance mode from the
    coordinate-level config."""

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 80
    tolerance: float = 1e-7
    regularization: RegularizationContext = RegularizationContext()
    reg_weight: float = 0.0
    down_sampling_rate: float = 1.0
    variance_type: VarianceComputationType = VarianceComputationType.NONE
    # Incremental training: weight of the Gaussian prior built from the
    # estimator's initial_model posterior (0 = plain warm start, no prior).
    # Reference ⟦PriorDistribution⟧ / incremental-training params.
    incremental_weight: float = 0.0

    def __post_init__(self):
        if self.incremental_weight < 0.0:
            raise ValueError(
                f"incremental_weight must be >= 0, got {self.incremental_weight}"
            )
        if not (0.0 < self.down_sampling_rate <= 1.0):
            raise ValueError(
                f"down_sampling_rate must be in (0, 1], got {self.down_sampling_rate}"
            )

    def problem(self, task: TaskType) -> GLMOptimizationProblem:
        return GLMOptimizationProblem(
            task=task,
            optimizer_type=self.optimizer_type,
            optimizer_config=OptimizerConfig(
                max_iterations=self.max_iterations, tolerance=self.tolerance
            ),
            regularization=self.regularization,
            reg_weight=self.reg_weight,
            variance_type=self.variance_type,
        )

    def with_reg_weight(self, w: float) -> "GLMOptimizationConfiguration":
        return dataclasses.replace(self, reg_weight=w)


# One full GAME optimization configuration: coordinate id -> its opt config.
GameOptimizationConfiguration = Mapping[str, GLMOptimizationConfiguration]


def reg_weight_sweep(
    base: GameOptimizationConfiguration,
    reg_weights: Mapping[str, Sequence[float]],
) -> list[dict[str, GLMOptimizationConfiguration]]:
    """Expand a base configuration into the cartesian product of per-coordinate
    regularization weights — how the reference's driver turns
    ``coordinate-config regularization weights {1, 10, 100}`` flags into the
    ``Seq[GameOptimizationConfiguration]`` passed to ``GameEstimator.fit``."""
    for cid in reg_weights:
        if cid not in base:
            raise ValueError(f"reg_weights names unknown coordinate {cid!r}")
    cids = sorted(reg_weights)
    combos = itertools.product(*(reg_weights[c] for c in cids))
    out = []
    for combo in combos:
        cfg = dict(base)
        for cid, w in zip(cids, combo):
            cfg[cid] = cfg[cid].with_reg_weight(w)
        out.append(cfg)
    return out
