"""Estimator/Transformer API layer — reference ⟦photon-api/.../estimators,
.../transformers⟧ (SURVEY.md §1 L6)."""
from photon_tpu.estimators.config import (
    CoordinateDataConfig,
    FactoredRandomEffectDataConfig,
    FixedEffectDataConfig,
    GameOptimizationConfiguration,
    GLMOptimizationConfiguration,
    RandomEffectDataConfig,
    reg_weight_sweep,
)
from photon_tpu.estimators.game_estimator import (
    GameEstimator,
    GameFitResult,
    build_re_dataset_from_bundle,
    select_best,
)
from photon_tpu.estimators.game_transformer import GameTransformer

__all__ = [
    "CoordinateDataConfig",
    "FactoredRandomEffectDataConfig",
    "FixedEffectDataConfig",
    "RandomEffectDataConfig",
    "GLMOptimizationConfiguration",
    "GameOptimizationConfiguration",
    "reg_weight_sweep",
    "GameEstimator",
    "GameFitResult",
    "GameTransformer",
    "build_re_dataset_from_bundle",
    "select_best",
]
