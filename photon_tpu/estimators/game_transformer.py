"""GameTransformer: score a dataset with a trained GameModel.

Parity: reference ⟦photon-api/.../transformers/GameTransformer.scala⟧
(SURVEY.md §2.2, §3.6): per coordinate, score the data and sum additively;
rows whose entity was unseen at training fall back to the zero model; optional
evaluation when the data carries labels.

TPU-first: fixed-effect scoring is one sparse matvec on the whole batch
(replication over the mesh replaces the coefficient broadcast); random-effect
scoring projects trained per-entity coefficients into the scoring dataset's
bucket structure host-side — the reference's model-RDD join by REId — then
scores each bucket with one vmapped gather-dot.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.estimators.config import (
    CoordinateDataConfig,
    FixedEffectDataConfig,
    RandomEffectDataConfig,
)
from photon_tpu.estimators.game_estimator import (
    _factorize_group_ids,
    build_re_dataset_from_bundle,
)
from photon_tpu.evaluation import EvaluationResults, EvaluationSuite
from photon_tpu.game.coordinates import FixedEffectModel
from photon_tpu.game.descent import GameModel
from photon_tpu.game.random_effect import RandomEffectModel
from photon_tpu.io.data_reader import GameDataBundle

Array = jax.Array

SCORE_KERNEL_NAME = "additive_score_rows"


class _ScoreKernelStats:
    """Back-compat alias for the old ``SCORE_KERNEL_STATS`` module dict.

    The raw ``{"traces": 0}`` global was bumped from batcher worker threads
    and read by the metrics loop with no lock; the count now lives in the
    process-wide ``obs`` registry (thread-safe, resettable, and exported as
    ``kernel_traces_total{kernel="additive_score_rows"}`` on the Prometheus
    endpoint). This view keeps ``SCORE_KERNEL_STATS["traces"]`` reads
    working for existing callers and tests.
    """

    def __getitem__(self, key: str) -> int:
        if key != "traces":
            raise KeyError(key)
        from photon_tpu.obs import retrace

        return retrace.traces(SCORE_KERNEL_NAME)

    def keys(self):
        return ("traces",)

    def __repr__(self) -> str:
        return f"{{'traces': {self['traces']}}}"


SCORE_KERNEL_STATS = _ScoreKernelStats()


@partial(jax.jit, static_argnames=("fixed_parts", "re_parts"))
def additive_score_rows(
    offsets,
    shard_idx,
    shard_val,
    fixed_ws,
    re_proj,
    re_coef,
    *,
    fixed_parts,
    re_parts,
):
    """The additive GAME score of B padded rows as ONE jitted program —
    the kernel shared by ``GameTransformer.transform_rows`` and the online
    serving scorer (``photon_tpu/serving/``), so batch and online scores
    cannot drift.

    ``offsets [B]``; ``shard_idx/shard_val``: shard → ELL row arrays
    ``[B, K]`` (ghost column == that shard's dim, value 0).
    ``fixed_ws``: coordinate → extended coefficient vector ``[D+1]`` (the
    trailing zero absorbs ghost gathers). ``re_proj/re_coef``: coordinate →
    per-row entity subspace ``[B, P]`` — sorted global columns (ghost pad ==
    dim) and the entity's trained coefficients in those slots; an all-ghost
    row IS the zero model (the unseen-entity fallback of the batch scorer).
    ``fixed_parts``/``re_parts`` are static ``((cid, shard), ...)`` tuples
    fixing which arrays combine.

    Per RE row the contribution is Σ_k val·w_e[idx_k] resolved by a
    binary search of the row's feature columns against the entity's sorted
    subspace — the serve-time analog of the transformer's host-side
    model-RDD join (SURVEY.md §3.6), shaped [B, K] for the accelerator.
    """
    # Traced-function body: runs once per distinct input signature, i.e.
    # once per XLA compilation. The retrace sentinel counts it and warns if
    # it fires after the serving warmup declared the shape ladder complete.
    from photon_tpu.obs import retrace

    retrace.note_trace(SCORE_KERNEL_NAME)
    total = offsets
    for cid, shard in fixed_parts:
        idx, val = shard_idx[shard], shard_val[shard]
        w_ext = fixed_ws[cid]
        total = total + jnp.sum(val * w_ext[idx], axis=1)
    for cid, shard in re_parts:
        proj, coef = re_proj[cid], re_coef[cid]
        if proj.shape[1] == 0:  # empty model: nothing to add (static shape)
            continue
        idx, val = shard_idx[shard], shard_val[shard]
        pos = jax.vmap(jnp.searchsorted)(proj, idx)
        pos = jnp.minimum(pos, proj.shape[1] - 1)
        hit = jnp.take_along_axis(proj, pos, axis=1) == idx
        cv = jnp.take_along_axis(coef, pos, axis=1)
        total = total + jnp.sum(
            jnp.where(hit, cv * val.astype(cv.dtype), 0.0), axis=1
        )
    return total


@dataclasses.dataclass(frozen=True)
class GameTransformer:
    """Bind a trained model to the per-coordinate data configs it was
    trained with (shard names + entity columns).

    ``mesh`` (optional): fixed-effect scoring — the rows × features matvec
    that dominates serve cost — runs with rows sharded over ``data_axis``
    (coefficients replicated, the reference's broadcast; SURVEY.md §3.6).
    Random-effect scoring stays replicated: its per-row cost is a tiny
    local-subspace gather-dot.
    """

    model: GameModel
    coordinate_data_configs: Mapping[str, CoordinateDataConfig]
    intercept_indices: Optional[Mapping[str, int]] = None
    mesh: Optional[object] = None
    data_axis: str = "data"
    # Attach the MXU-friendly sparse layouts before the fixed-effect scoring
    # matvec (no-op off-accelerator). The CHUNKED serve path disables this:
    # its tables' static shapes are data-dependent per chunk, which would
    # trade the one-compile stable-shape guarantee for a recompile per chunk.
    accelerator_paths: bool = True

    def _intercept_for(self, shard: str) -> Optional[int]:
        if self.intercept_indices is None:
            return None
        return self.intercept_indices.get(shard)

    def _score_fixed(self, m: FixedEffectModel, batch) -> Array:
        if self.mesh is None:
            if self.accelerator_paths:
                # No-op off-accelerator; on TPU the scoring matvec runs the
                # MXU-friendly layout instead of the generic gather.
                batch = batch.with_accelerator_paths()
            return m.score_batch(batch)
        from photon_tpu.parallel.mesh import pad_and_shard_batch

        # Scoring reads ONLY the features — pad/shard them alone (device-
        # side zero rows, contributing 0 to the matvec) instead of shipping
        # the three O(N) row columns the matvec never touches.
        n = batch.n_rows
        feats = pad_and_shard_batch(batch.features, self.mesh, self.data_axis)
        return feats.matvec(m.model.coefficients.means)[:n]

    def transform(self, data: GameDataBundle) -> Array:
        """Total additive score per row: offsets + Σ coordinate scores."""
        total = jnp.asarray(data.offsets, jnp.float32)
        for cid in self.model.keys():
            dcfg = self.coordinate_data_configs.get(cid)
            if dcfg is None:
                raise ValueError(
                    f"model coordinate {cid!r} has no data config; "
                    f"configs cover {sorted(self.coordinate_data_configs)}"
                )
            m = self.model[cid]
            if isinstance(dcfg, FixedEffectDataConfig):
                if not isinstance(m, FixedEffectModel):
                    raise TypeError(f"{cid!r}: fixed-effect config, {type(m)} model")
                total = total + self._score_fixed(
                    m, data.batch(dcfg.feature_shard)
                )
            elif isinstance(dcfg, RandomEffectDataConfig):
                if not isinstance(m, RandomEffectModel):
                    raise TypeError(f"{cid!r}: random-effect config, {type(m)} model")
                ds = build_re_dataset_from_bundle(
                    data, dcfg,
                    self._intercept_for(dcfg.feature_shard),
                    for_scoring=True,
                )
                total = total + m.score_new_dataset(ds)
            else:  # pragma: no cover - union is closed
                raise TypeError(f"unknown data config {type(dcfg)}")
        return total

    def transform_rows(self, data: GameDataBundle) -> Array:
        """Row-level scoring through the shared ``additive_score_rows``
        kernel — the same program the online serving scorer runs, so this is
        the parity anchor between batch and online scores (tested equal to
        ``transform``). Per-entity coefficients are joined host-side row by
        row (no bucket regrouping), which is the right shape for micro-batch
        serving and small scoring calls; large offline scans should prefer
        ``transform``'s bucketed path."""
        fixed_parts, re_parts = [], []
        fixed_ws, re_proj, re_coef = {}, {}, {}
        n = data.n_rows
        shard_idx = {s: jnp.asarray(f.idx) for s, f in data.features.items()}
        shard_val = {s: jnp.asarray(f.val) for s, f in data.features.items()}
        for cid in self.model.keys():
            dcfg = self.coordinate_data_configs.get(cid)
            if dcfg is None:
                raise ValueError(
                    f"model coordinate {cid!r} has no data config; "
                    f"configs cover {sorted(self.coordinate_data_configs)}"
                )
            m = self.model[cid]
            if isinstance(dcfg, FixedEffectDataConfig):
                w = m.model.coefficients.means
                fixed_ws[cid] = jnp.concatenate(
                    [w, jnp.zeros((1,), w.dtype)]
                )
                fixed_parts.append((cid, dcfg.feature_shard))
            elif isinstance(dcfg, RandomEffectDataConfig):
                keys = data.id_tags[dcfg.re_type]
                dim = data.features[dcfg.feature_shard].dim
                rows, width, by_key = [], 1, {}
                for key in keys:
                    hit = by_key.get(key)
                    if hit is None:
                        hit = m.coefficients_for(key)
                        by_key[key] = hit
                    rows.append(hit)
                    width = max(width, len(hit[0]))
                proj = np.full((n, width), dim, np.int32)
                # The model's own precision, not hardcoded f32: an f64
                # model must score identically through this path and
                # ``transform`` (the same dtype contract newton_re's
                # solvers honor).
                cdt = (np.asarray(m.bucket_coefs[0]).dtype
                       if len(m.bucket_coefs) else np.float32)
                coef = np.zeros((n, width), cdt)
                for r, (gi, gv) in enumerate(rows):
                    proj[r, : len(gi)] = gi
                    coef[r, : len(gi)] = gv
                re_proj[cid] = jnp.asarray(proj)
                re_coef[cid] = jnp.asarray(coef)
                re_parts.append((cid, dcfg.feature_shard))
            else:  # pragma: no cover - union is closed
                raise TypeError(f"unknown data config {type(dcfg)}")
        # AOT compile store (runtime/compile_store.py): the batch-scored
        # shape joins the manifest so restarts pre-warm it too.
        from photon_tpu.runtime.compile_store import dispatch_recorded

        return dispatch_recorded(
            SCORE_KERNEL_NAME, additive_score_rows,
            (jnp.asarray(data.offsets, jnp.float32), shard_idx, shard_val,
             fixed_ws, re_proj, re_coef),
            {"fixed_parts": tuple(fixed_parts),
             "re_parts": tuple(re_parts)})

    def transform_and_evaluate(
        self, data: GameDataBundle, suite: EvaluationSuite
    ) -> tuple[Array, EvaluationResults]:
        """Score + evaluate (reference: GameScoringDriver's optional
        evaluator list over the scored data)."""
        scores = self.transform(data)
        results = evaluate_scored_arrays(
            suite, scores, data.labels, data.weights, data.id_tags
        )
        return scores, results


def evaluate_scored_arrays(
    suite: EvaluationSuite, scores, labels, weights, id_tags: Mapping,
    factorized: Optional[Mapping] = None,
) -> EvaluationResults:
    """Evaluate precomputed scores: factorize each grouped evaluator's id
    column, cast to f32, run the suite. Shared by whole-dataset scoring
    (above) and the chunked scoring driver (which accumulates these arrays
    across streamed chunks).

    ``factorized`` maps a group column to ``(codes, n_groups)`` for callers
    that already hold dense int codes (the chunked driver dictionary-encodes
    per chunk); those columns skip the O(N log N) ``np.unique`` pass.
    """
    group_cols = {ev.group_column for ev in suite.evaluators if ev.group_column}
    gids, ngroups = {}, {}
    for col in group_cols:
        if factorized is not None and col in factorized:
            codes, n = factorized[col]
            gids[col] = jnp.asarray(np.asarray(codes, np.int32))
            ngroups[col] = int(n)
        elif col in id_tags:
            gids[col], ngroups[col] = _factorize_group_ids(id_tags[col])
        else:
            raise ValueError(f"grouped evaluator needs id column {col!r}")
    return suite.evaluate(
        jnp.asarray(scores, jnp.float32),
        jnp.asarray(labels, jnp.float32),
        jnp.asarray(weights, jnp.float32),
        gids or None,
        ngroups or None,
    )
