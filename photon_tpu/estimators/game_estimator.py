"""GameEstimator: end-to-end GAME fit over a configuration sweep.

Parity: reference ⟦photon-api/.../estimators/GameEstimator.scala⟧ (SURVEY.md
§3.2): DataFrame → per-coordinate datasets (built ONCE, reused across every
optimization configuration) → for each configuration, coordinate descent →
``Seq[(GameModel, Option[EvaluationResults], GameOptimizationConfiguration)]``.

TPU-first differences from the reference:
* per-coordinate datasets are device arrays (fixed-effect ``LabeledBatch``,
  bucketed ``RandomEffectDataset``) in one fixed global row order — the
  reference's GameDatum RDD partitioning/persist bookkeeping disappears;
* validation scoring per coordinate is a closure over pre-built validation
  structures, so coordinate descent's per-step evaluation does no joins;
* normalization contexts are computed from on-device feature statistics
  (one ``sq_rmatvec`` pass) instead of a Spark summarizer job.
"""
from __future__ import annotations

import dataclasses
import logging
import os
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
    context_from_statistics,
)
from photon_tpu.data.random_effect import (
    RandomEffectDataset,
    build_random_effect_dataset,
)
from photon_tpu.data.sampling import down_sampler_for_task
from photon_tpu.data.statistics import compute_feature_statistics
from photon_tpu.estimators.config import (
    CoordinateDataConfig,
    FactoredRandomEffectDataConfig,
    FixedEffectDataConfig,
    GameOptimizationConfiguration,
    GLMOptimizationConfiguration,
    RandomEffectDataConfig,
)
from photon_tpu.evaluation import EvaluationResults, EvaluationSuite
from photon_tpu.functions.objective import intercept_reg_mask
from photon_tpu.game.coordinates import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_tpu.game.descent import (
    CoordinateDescent,
    CoordinateStepRecord,
    GameModel,
    ValidationData,
)
from photon_tpu.io.data_reader import GameDataBundle
from photon_tpu.types import TaskType

Array = jax.Array

logger = logging.getLogger("photon_tpu.estimators")


@dataclasses.dataclass(frozen=True)
class GameFitResult:
    """One entry of the estimator's output sequence — reference
    ⟦(GameModel, Option[EvaluationResults], GameOptimizationConfiguration)⟧
    plus the per-step tracker."""

    model: GameModel
    evaluation: Optional[EvaluationResults]
    config: GameOptimizationConfiguration
    tracker: Sequence[CoordinateStepRecord]


def build_re_dataset_from_bundle(
    bundle: GameDataBundle,
    cfg: RandomEffectDataConfig,
    intercept_index: Optional[int] = None,
    for_scoring: bool = False,
) -> RandomEffectDataset:
    """Group a bundle's rows by ``cfg.re_type`` into a bucketed per-entity
    dataset. For scoring/validation datasets every entity is kept (rows of
    entities unseen at training time score 0 — the reference's zero-model
    fallback) and no active/passive split applies."""
    sf = bundle.features[cfg.feature_shard]
    if cfg.re_type not in bundle.id_tags:
        raise ValueError(
            f"random effect {cfg.re_type!r} needs id tag column "
            f"{cfg.re_type!r}; bundle has {sorted(bundle.id_tags)}"
        )
    val_np = np.asarray(jax.device_get(sf.val))
    # Follow the bundle's feature precision (float64 under --dtype float64)
    # — EXCEPT sub-f32 feed dtypes: the bf16 feed narrows the fixed-effect
    # transfer only, while per-entity solves accumulate in f32 (the batched
    # Cholesky kernels have no bf16 lowering), so RE buckets re-pack the
    # already-quantized values as f32.
    re_dtype = val_np.dtype
    if re_dtype.itemsize < 4:
        re_dtype = np.dtype(np.float32)
    return build_random_effect_dataset(
        re_type=cfg.re_type,
        entity_keys_per_row=bundle.id_tags[cfg.re_type],
        idx=np.asarray(jax.device_get(sf.idx)),
        val=val_np,
        labels=bundle.labels,
        global_dim=sf.dim,
        weights=bundle.weights,
        active_bound=None if for_scoring else cfg.active_bound,
        min_entity_rows=1 if for_scoring else cfg.min_entity_rows,
        intercept_index=intercept_index,
        max_features_per_entity=(
            None if for_scoring else cfg.max_features_per_entity
        ),
        max_bucket_entities=cfg.max_bucket_entities,
        host_resident=cfg.host_resident,
        dtype=re_dtype,
    )


def _factorize_group_ids(values: np.ndarray) -> tuple[Array, int]:
    keys, inv = np.unique(values, return_inverse=True)
    return jnp.asarray(inv.astype(np.int32)), len(keys)


@dataclasses.dataclass
class GameEstimator:
    """Configured GAME trainer; ``fit`` runs the configuration sweep.

    ``coordinate_data_configs`` fixes each coordinate's dataset; the
    ``update_sequence`` (default: insertion order) and sweep count mirror the
    reference params ⟦coordinateUpdateSequence, coordinateDescentIterations⟧.
    ``intercept_indices`` (shard → column) excludes intercepts from
    regularization and anchors normalization shifts, as the reference derives
    from its index maps.
    """

    task: TaskType
    coordinate_data_configs: Mapping[str, CoordinateDataConfig]
    update_sequence: Optional[Sequence[str]] = None
    n_sweeps: int = 1
    evaluator_specs: Sequence[str] = ()
    normalization: NormalizationType = NormalizationType.NONE
    intercept_indices: Optional[Mapping[str, int]] = None
    mesh: Optional[object] = None
    data_axis: str = "data"
    # Fixed-effect coordinates train feature-dimension-sharded over this
    # mesh axis when set (P3; random effects always shard over data_axis).
    model_axis: Optional[str] = None
    # Auto-routing (SURVEY.md §2.6 P3): when ``model_axis`` is unset but the
    # mesh HAS a "model" axis, fixed-effect coordinates whose feature dim
    # exceeds this threshold train feature-sharded; smaller ones stay
    # data-parallel (coefficients replicated over the model axis).
    auto_p3_threshold: int = 1 << 20
    # Device-resident sweep cache budget in MB for host-resident coordinate
    # data (data/device_cache.py): multi-sweep descent pins those datasets
    # on device after first touch instead of re-uploading every sweep.
    # None = PHOTON_SWEEP_CACHE_MB (default 2048); 0 disables.
    sweep_cache_mb: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        if self.update_sequence is None:
            self.update_sequence = tuple(self.coordinate_data_configs)
        for cid in self.update_sequence:
            if cid not in self.coordinate_data_configs:
                raise ValueError(
                    f"update sequence names unknown coordinate {cid!r}"
                )

    def fingerprint_parts(self) -> tuple:
        """The estimator's training-semantics identity, for checkpoint
        fingerprints (tuning resume refuses a changed configuration)."""
        return (
            self.task,
            tuple(self.update_sequence),
            self.n_sweeps,
            tuple(self.evaluator_specs),
            self.normalization,
            sorted((cid, repr(c))
                   for cid, c in self.coordinate_data_configs.items()),
        )

    # ------------------------------------------------------------------ fit

    def fit(
        self,
        data: GameDataBundle,
        validation_data: Optional[GameDataBundle] = None,
        configs: Sequence[GameOptimizationConfiguration] = (),
        initial_model: Optional[GameModel] = None,
        checkpoint_manager=None,
    ) -> list[GameFitResult]:
        """Train one GameModel per optimization configuration.

        Datasets, normalization contexts, and validation structures are built
        once and shared across the sweep (reference: datasets persist across
        the config loop and unpersist after). ``initial_model`` warm-starts
        every configuration (reference ⟦modelInputDirectory⟧).

        ``checkpoint_manager`` (photon_tpu.checkpoint.CheckpointManager)
        enables step-level checkpointing: every coordinate step and every
        completed configuration is snapshotted, and a fresh ``fit`` over the
        same inputs auto-resumes from the newest snapshot, reproducing the
        uninterrupted result bit-identically.
        """
        if not configs:
            raise ValueError("at least one GameOptimizationConfiguration required")
        for cfg in configs:
            missing = [c for c in self.update_sequence if c not in cfg]
            if missing:
                raise ValueError(f"configuration missing coordinates {missing}")

        suite = (
            EvaluationSuite.parse(self.evaluator_specs)
            if self.evaluator_specs
            else None
        )
        if validation_data is not None and suite is None:
            raise ValueError("validation data provided but no evaluator_specs")

        prep = self._prepare_cached(data)
        validation = (
            self._prepare_validation_cached(validation_data, suite)
            if validation_data is not None
            else None
        )

        results: list[GameFitResult] = []
        start_config, descent_resume, fingerprint = 0, None, None
        if checkpoint_manager is not None:
            from photon_tpu.checkpoint import run_fingerprint

            # One identity definition (fingerprint_parts — includes
            # normalization and data configs) plus the per-call specifics;
            # the tuning path shares the same parts, so both resume checks
            # refuse the same configuration changes.
            fingerprint = run_fingerprint((
                self.fingerprint_parts(),
                [sorted((cid, repr(c)) for cid, c in cfg.items())
                 for cfg in configs],
                data.n_rows,
            ))
            payload = checkpoint_manager.load_checked("game_fit", fingerprint)
            if payload is not None:
                meta = payload["meta"]
                results = list(payload["state"].get("completed_results", []))
                if meta.get("phase") == "config_done":
                    start_config = meta["config_index"] + 1
                else:
                    start_config = meta["config_index"]
                    descent_resume = payload
                logger.info(
                    "resuming from checkpoint step %d (config %d)",
                    payload["step"], start_config,
                )
                # Zero-recompile resume (docs/robustness.md §recovery
                # time): the checkpoint's compile-store manifest reference
                # pre-warms every executable the interrupted run compiled
                # BEFORE the first resumed step dispatches — the restart
                # cost becomes artifact I/O, not XLA.
                from photon_tpu.runtime.compile_store import (
                    prewarm_from_checkpoint,
                )

                prewarm_from_checkpoint(payload, logger_=logger)

        # Each config owns steps_per_config descent steps + 1 config-done slot.
        steps_per_config = self.n_sweeps * len(self.update_sequence)
        # One host-side MXU-layout build per distinct feature object across
        # the whole config sweep (id(features) -> attached features).
        accel_cache: dict = {}
        for i, cfg in enumerate(configs):
            if i < start_config:
                continue
            if i > start_config and os.environ.get(
                "PHOTON_CLEAR_CACHES_PER_CONFIG"
            ) == "1":
                # λ-boundary executable-cache bound (VERDICT r5 weak #5):
                # a long sweep accumulates mmap'd JIT code pages jax never
                # frees in-process; opt-in (the drivers'
                # --clear-caches-per-config) because in-core sweeps whose
                # shapes repeat across λ values benefit from reuse.
                from photon_tpu.supervisor import clear_executable_caches

                clear_executable_caches(f"config boundary {i}")
            logger.info("=== configuration %d/%d ===", i + 1, len(configs))
            coordinates = self._build_coordinates(
                prep, cfg, config_index=i, initial_model=initial_model,
                accel_cache=accel_cache,
            )
            descent = CoordinateDescent(
                update_sequence=tuple(self.update_sequence),
                n_sweeps=self.n_sweeps,
            )
            model, tracker = descent.run(
                coordinates,
                n_rows=data.n_rows,
                base_offsets=jnp.asarray(data.offsets, jnp.float32),
                validation=validation,
                suite=suite,
                initial_models=dict(initial_model.models) if initial_model else None,
                checkpointer=checkpoint_manager,
                resume=descent_resume if i == start_config else None,
                step_base=i * (steps_per_config + 1),
                checkpoint_meta={"config_index": i, "kind": "game_fit",
                                 "fingerprint": fingerprint},
                extra_state={"completed_results": results},
            )
            descent_resume = None
            evaluation = (
                self._evaluate(model, validation, suite)
                if validation is not None
                else None
            )
            results.append(GameFitResult(model, evaluation, cfg, tracker))
            if checkpoint_manager is not None:
                checkpoint_manager.save(
                    i * (steps_per_config + 1) + steps_per_config,
                    state={"completed_results": results},
                    meta={"phase": "config_done", "config_index": i,
                          "kind": "game_fit", "fingerprint": fingerprint},
                )
        if checkpoint_manager is not None:
            checkpoint_manager.wait()
        return results

    # ----------------------------------------------------------- internals

    def _intercept_for(self, shard: str) -> Optional[int]:
        if self.intercept_indices is None:
            return None
        return self.intercept_indices.get(shard)

    def _prepare_cached(self, data: GameDataBundle) -> dict:
        """Per-bundle preparation cache (size 1, identity-keyed): repeated
        fits on the same bundle — hyperparameter tuning calls fit once per
        proposed config — reuse the datasets/statistics instead of
        regrouping random effects every iteration."""
        cached = getattr(self, "_prep_cache", None)
        if cached is not None and cached[0] is data:
            return cached[1]
        if cached is not None:
            # New bundle: drop the old bundle's device pins (a tuning loop
            # switching datasets must not hold both residencies).
            old_cache = cached[1].get("device_cache")
            if old_cache is not None:
                old_cache.release()
        prep = self._prepare(data)
        self._prep_cache = (data, prep)
        return prep

    def _prepare_validation_cached(
        self, vdata: GameDataBundle, suite: EvaluationSuite
    ) -> ValidationData:
        cached = getattr(self, "_validation_cache", None)
        if cached is not None and cached[0] is vdata and cached[1] == suite:
            return cached[2]
        v = self._prepare_validation(vdata, suite)
        self._validation_cache = (vdata, suite, v)
        return v

    def _prepare(self, data: GameDataBundle) -> dict:
        """Build per-coordinate datasets + per-shard normalization ONCE."""
        from photon_tpu.data.device_cache import DeviceSweepCache

        prep: dict = {"train": {}, "norm": {}, "batches": {}}
        # One sweep cache per prepared bundle, shared across the whole
        # config sweep (same data ⇒ one upload for every λ). Mesh-attached:
        # pins shard over the entity axis (per-shard residency, per-device
        # budget × device count) instead of pinning to device 0.
        prep["device_cache"] = DeviceSweepCache(
            None if self.sweep_cache_mb is None
            else int(self.sweep_cache_mb * 1e6),
            mesh=self.mesh, entity_axis=self.data_axis,
        )
        shards_used = {
            c.feature_shard for c in self.coordinate_data_configs.values()
        }
        for shard in sorted(shards_used):
            batch = data.batch(shard)
            prep["batches"][shard] = batch
            if self.normalization != NormalizationType.NONE:
                stats = compute_feature_statistics(batch)
                prep["norm"][shard] = context_from_statistics(
                    stats, self.normalization, self._intercept_for(shard)
                )
            else:
                prep["norm"][shard] = None

        for cid, dcfg in self.coordinate_data_configs.items():
            if isinstance(dcfg, FixedEffectDataConfig):
                prep["train"][cid] = prep["batches"][dcfg.feature_shard]
            elif isinstance(dcfg, RandomEffectDataConfig):
                prep["train"][cid] = build_re_dataset_from_bundle(
                    data, dcfg, self._intercept_for(dcfg.feature_shard)
                )
            else:  # pragma: no cover - union is closed
                raise TypeError(f"unknown data config {type(dcfg)}")
        return prep

    def _build_coordinates(
        self,
        prep: dict,
        cfg: GameOptimizationConfiguration,
        config_index: int,
        initial_model: Optional[GameModel] = None,
        accel_cache: Optional[dict] = None,
    ) -> dict[str, Coordinate]:
        # Coordinates are built for EVERY data config, not just the update
        # sequence: coordinates outside the sequence are scoring-only (locked
        # warm-start models — reference partial retraining) and use a default
        # problem that never runs.
        coordinates: dict[str, Coordinate] = {}
        for cid in self.coordinate_data_configs:
            dcfg = self.coordinate_data_configs[cid]
            ocfg = cfg.get(cid, GLMOptimizationConfiguration())
            problem = ocfg.problem(self.task)
            intercept = self._intercept_for(dcfg.feature_shard)

            init_m = (
                initial_model.models.get(cid)
                if initial_model is not None and ocfg.incremental_weight > 0.0
                else None
            )
            if ocfg.incremental_weight > 0.0 and init_m is None:
                raise ValueError(
                    f"coordinate {cid!r}: incremental_weight > 0 requires an "
                    "initial_model containing this coordinate"
                )

            if isinstance(dcfg, FixedEffectDataConfig):
                batch: LabeledBatch = prep["train"][cid]
                mask = intercept_reg_mask(batch.dim, intercept)
                if mask is not None:
                    problem = dataclasses.replace(problem, reg_mask=mask)
                if init_m is not None:
                    from photon_tpu.functions.prior import PriorDistribution

                    problem = dataclasses.replace(
                        problem,
                        prior=PriorDistribution.from_model(
                            init_m.model.coefficients.means,
                            init_m.model.coefficients.variances,
                            ocfg.incremental_weight,
                        ),
                    )
                if ocfg.down_sampling_rate < 1.0:
                    # Per-(config, coordinate) derived key, reproducible.
                    key = jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.PRNGKey(self.seed), config_index
                        ),
                        len(coordinates),
                    )
                    sampler = down_sampler_for_task(
                        self.task, ocfg.down_sampling_rate
                    )
                    batch = sampler.down_sample(key, batch)
                model_axis = self.model_axis
                if (
                    model_axis is None
                    and self.mesh is not None
                    and "model" in getattr(self.mesh, "axis_names", ())
                    and batch.dim > self.auto_p3_threshold
                    # Route only configurations fit_model_parallel supports;
                    # others stay data-parallel (replicated over the model
                    # axis) instead of failing mid-sweep.
                    and problem.optimizer_type.name in ("LBFGS", "OWLQN", "TRON")
                    and problem.variance_type.name != "FULL"
                    and not (
                        prep["norm"][dcfg.feature_shard] is not None
                        and problem.prior is not None
                    )
                ):
                    model_axis = "model"
                if self.mesh is None:
                    # Single-device solve: attach the MXU-friendly sparse
                    # layouts (no-op off-accelerator; one host-side build
                    # per distinct feature object across the sweep). Mesh
                    # runs shard rows, which the global tables cannot
                    # follow — those keep the shardable plain formulation.
                    batch = batch.with_accelerator_paths(accel_cache)
                coordinates[cid] = FixedEffectCoordinate(
                    batch=batch,
                    problem=problem,
                    feature_shard=dcfg.feature_shard,
                    mesh=self.mesh,
                    data_axis=self.data_axis,
                    normalization=prep["norm"][dcfg.feature_shard],
                    model_axis=model_axis,
                )
            elif isinstance(dcfg, FactoredRandomEffectDataConfig):
                from photon_tpu.game.coordinates import (
                    FactoredRandomEffectCoordinate,
                )

                # Unsupported knobs fail loudly rather than silently no-op.
                unsupported = []
                if ocfg.incremental_weight > 0.0:
                    unsupported.append("incremental training")
                if ocfg.down_sampling_rate < 1.0:
                    unsupported.append("down-sampling")
                if ocfg.variance_type.name != "NONE":
                    unsupported.append("coefficient variances")
                if prep["norm"][dcfg.feature_shard] is not None:
                    unsupported.append("feature normalization")
                if unsupported:
                    raise ValueError(
                        f"coordinate {cid!r}: {', '.join(unsupported)} "
                        "not supported for factored random effects"
                    )
                coordinates[cid] = FactoredRandomEffectCoordinate(
                    dataset=prep["train"][cid],
                    problem=problem,
                    latent_dim=dcfg.latent_dim,
                    n_alternations=dcfg.n_alternations,
                    seed=self.seed,
                )
            else:
                dataset = prep["train"][cid]
                if ocfg.down_sampling_rate < 1.0:
                    from photon_tpu.data.random_effect import down_sample_dataset

                    key = jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.PRNGKey(self.seed), config_index
                        ),
                        len(coordinates),
                    )
                    dataset = down_sample_dataset(
                        dataset,
                        down_sampler_for_task(self.task, ocfg.down_sampling_rate),
                        key,
                    )
                mask = intercept_reg_mask(dataset.global_dim, intercept)
                priors = None
                if init_m is not None:
                    from photon_tpu.functions.prior import PriorDistribution

                    # Posterior projection is config-independent (down-sampled
                    # datasets keep the bucket structure); cache it across the
                    # sweep. Keyed by the model object itself (identity
                    # verified on hit) — an id() key could silently serve a
                    # stale projection after id reuse.
                    cache = prep.setdefault("prior_proj", {})
                    hit = cache.get(cid)
                    if hit is None or hit[0] is not init_m:
                        hit = (
                            init_m,
                            init_m.project_posteriors_to(prep["train"][cid]),
                        )
                        cache[cid] = hit
                    means, variances = hit[1]
                    priors = [
                        PriorDistribution.from_model(
                            m, v, ocfg.incremental_weight
                        )
                        for m, v in zip(means, variances)
                    ]
                coordinates[cid] = RandomEffectCoordinate(
                    dataset=dataset,
                    problem=problem,
                    mesh=self.mesh,
                    entity_axis=self.data_axis,
                    global_reg_mask=mask,
                    normalization=prep["norm"][dcfg.feature_shard],
                    priors=priors,
                    # The sweep cache pins ONLY the shared prepared dataset:
                    # a down-sampled dataset is a fresh object per config,
                    # and pinning each would stack one dead mirror per λ in
                    # device memory for the estimator's lifetime. Those
                    # configs stream per sweep — the pre-cache behavior.
                    device_cache=(
                        prep.get("device_cache")
                        if ocfg.down_sampling_rate >= 1.0 else None
                    ),
                )
        return coordinates

    def _prepare_validation(
        self,
        vdata: GameDataBundle,
        suite: EvaluationSuite,
    ) -> ValidationData:
        """Validation rows + per-coordinate scorers + grouped-eval ids."""
        v_batches = {
            s: vdata.batch(s)
            for s in {c.feature_shard for c in self.coordinate_data_configs.values()}
        }
        scorers: dict = {}
        for cid, dcfg in self.coordinate_data_configs.items():
            if isinstance(dcfg, FixedEffectDataConfig):
                vb = v_batches[dcfg.feature_shard]
                scorers[cid] = lambda m, vb=vb: m.score_batch(vb)
            else:
                v_ds = build_re_dataset_from_bundle(
                    vdata,
                    dcfg,
                    self._intercept_for(dcfg.feature_shard),
                    for_scoring=True,
                )
                scorers[cid] = lambda m, v_ds=v_ds: m.score_new_dataset(v_ds)

        group_cols = {
            ev.group_column
            for ev in suite.evaluators
            if ev.group_column is not None
        }
        gids, ngroups = {}, {}
        for col in group_cols:
            if col not in vdata.id_tags:
                raise ValueError(
                    f"grouped evaluator needs id tag column {col!r} in "
                    f"validation data; bundle has {sorted(vdata.id_tags)}"
                )
            gids[col], ngroups[col] = _factorize_group_ids(vdata.id_tags[col])

        return ValidationData(
            labels=jnp.asarray(vdata.labels, jnp.float32),
            weights=jnp.asarray(vdata.weights, jnp.float32),
            offsets=jnp.asarray(vdata.offsets, jnp.float32),
            scorers=scorers,
            group_ids_by_column=gids or None,
            num_groups_by_column=ngroups or None,
        )

    def _evaluate(
        self,
        model: GameModel,
        validation: ValidationData,
        suite: EvaluationSuite,
    ) -> EvaluationResults:
        scores = validation.offsets + sum(
            validation.scorers[cid](model[cid]) for cid in model.keys()
        )
        return suite.evaluate(
            scores,
            validation.labels,
            validation.weights,
            validation.group_ids_by_column,
            validation.num_groups_by_column,
        )


def select_best(
    results: Sequence[GameFitResult], suite: EvaluationSuite
) -> GameFitResult:
    """Pick the configuration whose final validation primary metric is best —
    the reference driver's model-selection step (SURVEY.md §3.1)."""
    scored = [r for r in results if r.evaluation is not None]
    if not scored:
        return results[0]
    best = scored[0]
    for r in scored[1:]:
        if suite.primary.better_than(r.evaluation.primary, best.evaluation.primary):
            best = r
    return best
