"""photon-tpu: a TPU-native framework with the capabilities of photon-ml.

A from-scratch JAX/XLA rebuild of the reference
(TheClimateCorporation/photon-ml, LinkedIn-lineage GLM + GAME/GLMix on
Spark/Scala — see SURVEY.md): generalized linear models (logistic, linear,
Poisson, smoothed-hinge SVM), batch second-order optimizers (L-BFGS, OWL-QN,
TRON) running as single on-device XLA loops, and GAME mixed-effect models
(fixed effect + per-entity random effects via coordinate descent) with
data-parallel `psum` gradients and `vmap`-batched entity solves sharded over a
`jax.sharding.Mesh`.
"""

__version__ = "0.1.0"

from photon_tpu.types import TaskType  # noqa: F401
